"""Batched BLS12-381 base-field arithmetic in JAX — the TPU limb kernel core.

This is the device analog of blst's C/assembly fp arithmetic (the reference
consumes it via crypto/bls/src/impls/blst.rs); every higher layer of the TPU
backend (Fp2/Fp6/Fp12 tower, curve ops, pairing) is built on the ops here and
is differentially tested against the pure-Python oracle (fields.py).

Representation: lazy reduction with static bound tracking
---------------------------------------------------------
An Fp element is an ``LFp(limbs, bound)``: 26 x 15-bit limbs, little-endian,
each in a uint32 lane, shape ``(26, *batch)`` — the limb axis LEADS so the
trailing batch axis rides the TPU's 128-wide vector lanes.  Montgomery domain
with R = 2^390.  ``bound`` is a STATIC (trace-time) upper bound on the value
in units of P; it is pytree aux data, so it travels through jit/scan/select
and mismatches surface as loud trace-time errors, never silent corruption.

Limbs are only *quasi-normalized* (<= 2^15 + 2^7) and values are bounded by
small multiples of P rather than reduced mod P.  This removes every
sequential carry chain from additions and subtractions (one vector add plus
a two-op carry "compress"), which keeps both the XLA graph and the VPU work
per op small.  Op contracts:

* ``fp_add``: value a+b, bound a.bound + b.bound.
* ``fp_sub``: value a - b + k*P where k (a power of two >= b.bound) is
  chosen automatically; the precomputed biased k*P has every non-top limb
  >= any quasi limb, and ``_k_for`` additionally requires the (borrowed)
  top bias limb to dominate the subtrahend's value-capped top limb, so no
  column subtraction can go negative.
* ``mont_mul``: requires a.bound * b.bound <= 2000 (checked at trace time);
  output has STRICT limbs and bound a.bound*b.bound/625 + 1.1 (< 4.3).
  (P/R ~ 2^-9.3 ~ 1/625.)
* ``fp_reduce(x) = mont_mul(x, R mod P)``: value-preserving mod P, bound
  back to < 2 — inserted at op boundaries (tower/point outputs) so bounds
  cannot creep and scan carries keep a stable static bound.
* Canonical form (value < P) exists only at the edges: ``fp_canon`` for
  equality tests, host codecs for I/O.

Multiplication is schoolbook via a Horner scan (acc = acc*2^15 + a_i*b) with
32-bit partial products split at 15 bits before accumulation (column sums
< 2^21, no uint32 overflow).  Montgomery reduction is m = T*P' mod R;
(T + m*P)/R, with ONE sequential carry normalization at the end — the only
per-limb chain in the hot path.
"""

from __future__ import annotations

import functools
import math
from fractions import Fraction

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import params

# ---------------------------------------------------------------------------
# Constants
# ---------------------------------------------------------------------------

BITS = 15
N = 26  # 26 * 15 = 390 bits >= 381
MASK = (1 << BITS) - 1
QMAX = (1 << BITS) + (1 << 7)  # quasi-normalized limb bound
U32 = jnp.uint32

MAX_MUL_PRODUCT = 2000.0  # max a.bound * b.bound entering mont_mul
MAX_BOUND = 500.0  # max value bound anywhere (keeps top limb small)

# Montgomery output-bound model: mont_mul emits bound
# prod / MONT_DIVISOR + MONT_EPS where prod = a.bound * b.bound.  The
# exact bound is prod * P/R + 1 with R/P = 630.0525..., so divisor 625
# with intercept 1.1 over-covers by 2.9% at prod = MAX_MUL_PRODUCT —
# machine-checked by analysis/range_lint ("mont-output-bound").
MONT_DIVISOR = 625.0
MONT_EPS = 1.1
# fp_reduce pins its output label here; the exact worst case is
# MAX_BOUND * P/R + 1 = 1.794 (range_lint "reduce-pin").
REDUCE_PIN = 2.0

P_INT = params.P
R_INT = 1 << (BITS * N)  # Montgomery radix 2^390
assert R_INT > 512 * P_INT
R1_INT = R_INT % P_INT  # 1 in Montgomery form
R2_INT = R_INT * R_INT % P_INT
PPRIME_INT = (-pow(P_INT, -1, R_INT)) % R_INT  # -P^-1 mod R

_BIAS_KS = (2, 4, 8, 16, 32, 64, 128, 256)


class LFp:
    """Lazy field element: quasi-normalized limbs + static value bound (in
    units of P).  Registered as a pytree with ``bound`` as aux data."""

    __slots__ = ("limbs", "bound")

    def __init__(self, limbs, bound: float):
        self.limbs = limbs
        self.bound = bound

    def __repr__(self):
        return f"LFp(shape={getattr(self.limbs, 'shape', None)}, bound={self.bound})"


def _lfp_flatten(x):
    return (x.limbs,), x.bound


def _lfp_unflatten(bound, children):
    return LFp(children[0], bound)


jax.tree_util.register_pytree_node(LFp, _lfp_flatten, _lfp_unflatten)


def int_to_limbs(x: int) -> np.ndarray:
    """Host codec: non-negative int < 2^390 -> (N,) uint32 strict limbs."""
    assert 0 <= x < R_INT
    return np.array([(x >> (BITS * i)) & MASK for i in range(N)], dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(v) << (BITS * i) for i, v in enumerate(arr))


def ints_to_limbs(xs) -> np.ndarray:
    """Host codec, vectorized: ints -> (N, B) limb columns.  int.to_bytes
    is C-speed; the 8-bit -> 15-bit regrouping is one unpackbits reshape
    (the per-int Python limb loop was a marshal bottleneck at B=4096)."""
    B = len(xs)
    if B == 0:
        return np.zeros((N, 0), dtype=np.uint32)
    raw = np.frombuffer(
        b"".join(x.to_bytes(49, "little") for x in xs), dtype=np.uint8
    ).reshape(B, 49)
    # bits in little-endian significance order per value
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, : N * BITS]
    weights = (1 << np.arange(BITS, dtype=np.uint32))
    limbs = (bits.reshape(B, N, BITS) * weights[None, None, :]).sum(
        axis=2, dtype=np.uint32
    )
    return np.ascontiguousarray(limbs.T)


def limbs_to_ints(limbs) -> list[int]:
    arr = np.asarray(limbs)
    flat = arr.reshape(N, -1)
    return [limbs_to_int(flat[:, j]) for j in range(flat.shape[1])]


def _biased_kp(k: int) -> np.ndarray:
    """k*P with every non-top limb boosted to >= QMAX by borrowing from the
    limb above, so (a + bias - b) is column-wise non-negative for quasi b.

    The boosting borrows exactly one unit into the top limb, lowering it
    to floor(k*P / 2^375) - 1 — which is why ``k >= b.bound`` alone does
    NOT guarantee top-column domination; ``_k_for`` additionally enforces
    ``_sub_top_dominates`` (machine-checked by range_lint "bias-k*")."""
    limbs = [int(v) for v in int_to_limbs(k * P_INT)]
    for i in range(N - 1):
        while limbs[i] < QMAX:
            limbs[i] += 1 << BITS
            limbs[i + 1] -= 1
    assert limbs[N - 1] >= 0, f"bias top limb underflow for k={k}"
    assert sum(v << (BITS * i) for i, v in enumerate(limbs)) == k * P_INT
    return np.array(limbs, dtype=np.uint32)


P_LIMBS = jnp.asarray(int_to_limbs(P_INT))
PPRIME_LIMBS = jnp.asarray(int_to_limbs(PPRIME_INT))
ONE_MONT = jnp.asarray(int_to_limbs(R1_INT))
_BIAS_NP = {k: _biased_kp(k) for k in _BIAS_KS}
BIAS = {k: jnp.asarray(v) for k, v in _BIAS_NP.items()}


def bcast(const, batch_shape) -> jnp.ndarray:
    return jnp.broadcast_to(
        const.reshape((N,) + (1,) * len(batch_shape)), (N,) + tuple(batch_shape)
    )


def zero_like(a: LFp) -> LFp:
    return LFp(jnp.zeros_like(a.limbs), 0.0)


def one_like(a: LFp) -> LFp:
    return LFp(bcast(ONE_MONT, a.limbs.shape[1:]), 1.0)


def batch_shape(a: LFp):
    return a.limbs.shape[1:]


# ---------------------------------------------------------------------------
# Carry handling (raw limb arrays)
# ---------------------------------------------------------------------------


def compress1(cols):
    """One carry pass: quasi-normalizes column sums < 2^16.6 (worst case
    is fp_sub: quasi a + boosted bias limb <= 32896 + 65663 = 98559, so
    hi <= 3 and outputs stay <= MASK + 3 <= QMAX).  The top limb's carry
    is statically impossible: any value < MAX_BOUND*P has top column
    <= floor(MAX_BOUND*P / 2^375) = 26142 < 2^15 (range_lint
    "compress1-top-carry")."""
    lo = cols & MASK
    hi = cols >> BITS
    return lo.at[1:].add(hi[:-1])


def compress2(cols):
    """Two passes: quasi-normalizes column sums < 2^21 (Horner output)."""
    return compress1(compress1(cols))


def full_chain(cols):
    """Sequential full normalization to strict limbs — the one per-limb
    chain, used once per mont_mul."""
    init = jnp.zeros(cols.shape[1:], dtype=U32)

    def step(c, col):
        t = col + c
        return t >> BITS, t & MASK

    carry, limbs = lax.scan(step, init, cols)
    del carry
    return limbs


def sub_chain(x, y):
    """Limb-wise x - y with borrow (strict inputs); (diff, borrow)."""
    init = jnp.zeros(x.shape[1:], dtype=U32)

    def step(bor, xy):
        x_k, y_k = xy
        t = x_k + U32(1 << BITS) - y_k - bor
        return U32(1) - (t >> BITS), t & MASK

    borrow, limbs = lax.scan(step, init, (x, y))
    return limbs, borrow


# ---------------------------------------------------------------------------
# Add / sub / neg (chain-free)
# ---------------------------------------------------------------------------


def _check_bound(b: float, who: str):
    assert b <= MAX_BOUND, f"{who}: value bound {b} exceeds {MAX_BOUND}P"


def fp_add(a: LFp, b: LFp) -> LFp:
    out = a.bound + b.bound
    _check_bound(out, "fp_add")
    return LFp(compress1(a.limbs + b.limbs), out)


def _sub_top_dominates(bound: float, k: int) -> bool:
    """Exact (Fraction) check that the k bias dominates every quasi
    subtrahend of value bound ``bound`` in the TOP column too: such a
    value's limb 25 is at most floor(bound*P / 2^375), which must not
    exceed the bias top limb.  ``k >= bound`` alone is insufficient —
    ``_biased_kp`` borrows one unit out of the top limb, so e.g. a
    bound-2.0 subtrahend can carry top limb 104 against the k=2 bias
    top of 103, wrapping the uint32 column."""
    top = int(_BIAS_NP[k][N - 1])
    return Fraction(bound) * P_INT < (top + 1) << (BITS * (N - 1))


@functools.lru_cache(maxsize=None)
def _k_for(bound: float) -> int:
    """Smallest bias constant k with k >= bound AND top-limb domination
    (see _sub_top_dominates).  Shared by the XLA ops and the fused
    Pallas kernels — both paths must pick identical k or the fused/XLA
    bit-equality contract breaks."""
    for k in _BIAS_KS:
        if k >= bound and _sub_top_dominates(bound, k):
            return k
    raise AssertionError(f"no safe bias constant for bound {bound}")


@functools.lru_cache(maxsize=None)
def sub_bias_max_bound(k: int) -> float:
    """Largest float subtrahend bound the k bias provably dominates (and
    thus the largest _k_for routes to k).  The range prover quantifies
    the per-k fp_sub/ksub proof obligations at exactly this edge."""
    top = int(_BIAS_NP[k][N - 1])
    f = min(float(k), float(Fraction((top + 1) << (BITS * (N - 1)), P_INT)))
    while f > 0 and not (f <= k and _sub_top_dominates(f, k)):
        f = float(np.nextafter(f, 0.0))
    return f


def fp_sub(a: LFp, b: LFp) -> LFp:
    """Value a - b + k*P, k auto-chosen so the bias dominates b column-
    wise (k >= b.bound for the value, _sub_top_dominates for limb 25)."""
    k = _k_for(b.bound)
    out = a.bound + k
    _check_bound(out, "fp_sub")
    bias = bcast(BIAS[k], a.limbs.shape[1:])
    return LFp(compress1(a.limbs + bias - b.limbs), out)


def fp_neg(a: LFp) -> LFp:
    k = _k_for(a.bound)
    bias = bcast(BIAS[k], a.limbs.shape[1:])
    return LFp(compress1(bias - a.limbs), float(k))


def fp_dbl(a: LFp) -> LFp:
    return fp_add(a, a)


def fp_select(mask, a: LFp, b: LFp) -> LFp:
    """mask over batch shape: a where mask else b (bound = max)."""
    return LFp(jnp.where(mask[None], a.limbs, b.limbs), max(a.bound, b.bound))


def relabel(a: LFp, bound: float) -> LFp:
    """Weaken the bound label (bound may only increase)."""
    assert bound >= a.bound
    return LFp(a.limbs, bound)


# ---------------------------------------------------------------------------
# Multiplication
# ---------------------------------------------------------------------------


def _mul_cols_wide(a_limbs, b_limbs):
    nb = a_limbs.shape[1:]
    acc0 = jnp.zeros((2 * N,) + nb, dtype=U32)

    def step(acc, a_i):
        p = a_i[None] * b_limbs
        plo = p & MASK
        phi = p >> BITS
        acc = jnp.concatenate([jnp.zeros_like(acc[:1]), acc[:-1]], axis=0)
        acc = acc.at[:N].add(plo)
        acc = acc.at[1 : N + 1].add(phi)
        return acc, None

    acc, _ = lax.scan(step, acc0, jnp.flip(a_limbs, 0))
    return compress2(acc)


def _mul_cols_low(a_limbs, b_limbs):
    nb = a_limbs.shape[1:]
    acc0 = jnp.zeros((N,) + nb, dtype=U32)

    def step(acc, a_i):
        p = a_i[None] * b_limbs
        plo = p & MASK
        phi = p >> BITS
        acc = jnp.concatenate([jnp.zeros_like(acc[:1]), acc[:-1]], axis=0)
        acc = acc + plo
        acc = acc.at[1:].add(phi[:-1])
        return acc, None

    acc, _ = lax.scan(step, acc0, jnp.flip(a_limbs, 0))
    return compress2(acc)


_PALLAS_MODE: bool | None = None


def pallas_enabled() -> bool:
    """Route mont_mul through the fused Pallas kernel (pallas_fp.py).

    Default: ON for TPU backends (12.5x measured over the scan path,
    PERF.md), OFF on CPU where the scan path is the fast oracle and
    Pallas would run interpreted.  LIGHTHOUSE_TPU_PALLAS=1/0 overrides."""
    global _PALLAS_MODE
    if _PALLAS_MODE is None:
        import os

        val = os.environ.get("LIGHTHOUSE_TPU_PALLAS")
        if val is not None:
            _PALLAS_MODE = val == "1"
        else:
            _PALLAS_MODE = jax.default_backend() == "tpu"
    return _PALLAS_MODE


def set_pallas(enabled: bool) -> None:
    global _PALLAS_MODE
    _PALLAS_MODE = enabled


_FORCE_DEVICE_PATHS = False


def set_force_device_paths(enabled: bool) -> None:
    """Treat the backend as a TPU for routing decisions (the *_active()
    gates) regardless of jax.default_backend().  For CPU-side tracing and
    auditing of the exact device composition (tools/dispatch_audit.py):
    pallas calls reached this way must run with interpret=True or be
    abstractly traced, never Mosaic-compiled."""
    global _FORCE_DEVICE_PATHS
    _FORCE_DEVICE_PATHS = enabled


def _device_backend() -> bool:
    return _FORCE_DEVICE_PATHS or jax.default_backend() == "tpu"


_CHAINS_MODE: bool | None = None


def chains_enabled() -> bool:
    """LIGHTHOUSE_TPU_CHAINS=1 routes static-exponent chains through the
    chunked Pallas chain kernels (interpret-proven; flips to default-on
    once measured on hardware)."""
    global _CHAINS_MODE
    if _CHAINS_MODE is None:
        import os

        _CHAINS_MODE = os.environ.get("LIGHTHOUSE_TPU_CHAINS", "") == "1"
    return _CHAINS_MODE


def set_chains(enabled: bool) -> None:
    """In-process A/B toggle (mirrors set_pallas)."""
    global _CHAINS_MODE
    _CHAINS_MODE = enabled


def chains_active() -> bool:
    """The ONE gate for chain-kernel routing (fp_pow, h2c fp2 chains):
    pallas on + chains opted in + a real TPU backend."""
    return (
        pallas_enabled() and chains_enabled() and _device_backend()
    )


_WSM_MODE: bool | None = None


def wsm_enabled() -> bool:
    """LIGHTHOUSE_TPU_WSM=1 routes the 64-bit weight scalar muls through
    the fused double-and-add step kernels (pallas_wsm.py;
    interpret-proven — flips to default-on once measured on hardware).
    After the fused Miller loop these became the dispatch leader
    (~900 stacked pallas calls per batch)."""
    global _WSM_MODE
    if _WSM_MODE is None:
        import os

        _WSM_MODE = os.environ.get("LIGHTHOUSE_TPU_WSM", "") == "1"
    return _WSM_MODE


def set_wsm(enabled: bool) -> None:
    """In-process A/B toggle (mirrors set_chains)."""
    global _WSM_MODE
    _WSM_MODE = enabled


def wsm_fused_active() -> bool:
    """Gate for the fused scalar-mul step kernels: pallas on + opted in
    + a real TPU backend (interpret mode is reached explicitly by
    tests)."""
    return (
        pallas_enabled() and wsm_enabled() and _device_backend()
    )


_MILLER_MODE: bool | None = None


def miller_enabled() -> bool:
    """Fused Miller-step kernels (pallas_miller.py): DEFAULT ON since the
    r5 on-chip A/B (3,061 vs 2,607 sets/s at B=512; 6,221 at B=8192 —
    TPU_SESSION_r05.jsonl).  LIGHTHOUSE_TPU_MILLER=0 reverts to the
    stacked per-op pallas calls."""
    global _MILLER_MODE
    if _MILLER_MODE is None:
        import os

        _MILLER_MODE = os.environ.get("LIGHTHOUSE_TPU_MILLER", "1") == "1"
    return _MILLER_MODE


def set_miller(enabled: bool) -> None:
    """In-process A/B toggle (mirrors set_chains)."""
    global _MILLER_MODE
    _MILLER_MODE = enabled


def miller_fused_active() -> bool:
    """Gate for the fused Miller-step kernels: pallas on + opted in + a
    real TPU backend (interpret mode is reached explicitly by tests)."""
    return (
        pallas_enabled() and miller_enabled() and _device_backend()
    )


_MXU_MODE: bool | None = None
_MXU_PLAN: dict | None = None


def mxu_enabled() -> bool:
    """Routes every Montgomery product — the standalone mont_mul kernel,
    the megachains, and the fused Miller loop — through the 13-bit
    re-limbed dot-product core (pallas_mxu.py) that runs the schoolbook
    column accumulation on the MXU instead of the VPU.  Interpret-proven
    byte-identical to the VPU kernels and range-proven under the int32
    2^31 MXU budget.

    Resolution precedence: ``set_mxu`` in-process override (A/B sweeps)
    > ``LIGHTHOUSE_TPU_MXU`` env flag (explicit operator override) >
    installed autotuned plan default (``install_mxu_plan``, the largest
    tuned shape's arm) > off.  The env flag is read live — an unset flag
    never latches, so a plan installed later (prewarm) is not shadowed."""
    if _MXU_MODE is not None:
        return _MXU_MODE
    import os

    env = os.environ.get("LIGHTHOUSE_TPU_MXU")
    if env is not None:
        return env == "1"
    if _MXU_PLAN is not None:
        default = _MXU_PLAN.get("*")
        if default is not None:
            return bool(default)
    return False


def set_mxu(enabled: bool | None) -> bool | None:
    """In-process A/B override (mirrors set_chains).  Beats both the env
    flag and any installed autotuned plan; ``None`` clears the override.
    Returns the previous override so callers can restore it exactly."""
    global _MXU_MODE
    prev = _MXU_MODE
    _MXU_MODE = None if enabled is None else bool(enabled)
    return prev


def install_mxu_plan(shapes: dict | None) -> None:
    """Install the autotuned per-shape arm plan (autotune.install_plan's
    seam): ``shapes`` maps padded batch size -> route-through-MXU, plus
    an optional ``"*"`` default for off-plan shapes.  ``None`` clears.
    Overrides (``set_mxu`` / ``LIGHTHOUSE_TPU_MXU``) still win — see
    ``mxu_enabled``."""
    global _MXU_PLAN
    _MXU_PLAN = dict(shapes) if shapes else None


def mxu_planned(batch) -> bool | None:
    """The installed plan's arm for padded batch ``batch``, or ``None``
    when no plan binds that shape or an explicit override (set_mxu / env
    flag) is active — overrides force one arm for *every* shape."""
    if _MXU_MODE is not None:
        return None
    import os

    if os.environ.get("LIGHTHOUSE_TPU_MXU") is not None:
        return None
    if _MXU_PLAN is None:
        return None
    routed = _MXU_PLAN.get(batch)
    if routed is None:
        routed = _MXU_PLAN.get("*")
    return None if routed is None else bool(routed)


def mxu_for_batch(batch) -> bool:
    """The arm the compiled program for padded batch ``batch`` should
    trace under: the planned arm when a plan binds, the process-wide
    gate otherwise.  This is what ``JaxBackend._kernel`` keys its cache
    and fingerprints on — the plan is resolved here, at lookup/compile
    time, never per dispatched batch."""
    planned = mxu_planned(batch)
    return mxu_enabled() if planned is None else planned


def mxu_active() -> bool:
    """Gate for the MXU dot-product Montgomery core: pallas on + opted
    in + a real TPU backend (interpret mode is reached explicitly by
    tests and the CPU bench fallback)."""
    return (
        pallas_enabled() and mxu_enabled() and _device_backend()
    )


def mont_mul(a: LFp, b: LFp) -> LFp:
    """Montgomery product a*b*R^-1 mod P (strict limbs out)."""
    prod = a.bound * b.bound
    assert prod <= MAX_MUL_PRODUCT, (
        f"mont_mul input bound product {prod} > {MAX_MUL_PRODUCT}; "
        "insert fp_reduce on an operand"
    )
    if pallas_enabled():
        from . import pallas_fp

        batch = a.limbs.shape[1:]
        flat = pallas_fp.mont_mul_limbs(
            a.limbs.reshape(N, -1),
            b.limbs.reshape(N, -1),
            # the kernel is Mosaic/TPU-only: interpret everywhere else
            interpret=jax.default_backend() != "tpu",
        )
        return LFp(flat.reshape((N,) + batch), prod / MONT_DIVISOR + MONT_EPS)
    t = _mul_cols_wide(a.limbs, b.limbs)
    m = _mul_cols_low(t[:N], bcast(PPRIME_LIMBS, a.limbs.shape[1:]))
    u = _mul_cols_wide(m, bcast(P_LIMBS, a.limbs.shape[1:]))
    s = full_chain(t + u)  # low N limbs are exactly zero (divisible by R)
    return LFp(s[N:], prod / MONT_DIVISOR + MONT_EPS)


def mont_sqr(a: LFp) -> LFp:
    return mont_mul(a, a)


def fp_reduce(x: LFp) -> LFp:
    """Value-preserving (mod P) reduction.  The output bound is pinned to
    REDUCE_PIN = 2.0 (exact worst case MAX_BOUND*P/R + 1 = 1.794; the
    formula bound x.bound/MONT_DIVISOR + MONT_EPS <= 1.9 for in-range x)
    so reduced values have a STABLE static bound — required for lax.scan
    carries, whose pytree aux must match between iterations."""
    out = mont_mul(x, one_like(x))
    assert out.bound <= REDUCE_PIN
    return LFp(out.limbs, REDUCE_PIN)


def guard_le(x: LFp, m: float) -> LFp:
    """Reduce x iff its bound exceeds m (trace-time decision)."""
    return fp_reduce(x) if x.bound > m else x


def fp_canon(x: LFp):
    """Canonical raw limbs (strict, value < P) for equality/serialization."""
    if x.bound > 2.0:
        x = fp_reduce(x)
    limbs = x.limbs
    p = bcast(P_LIMBS, limbs.shape[1:])
    d, borrow = sub_chain(limbs, p)
    return jnp.where((borrow == 0)[None], d, limbs)


def fp_eq(a: LFp, b: LFp):
    return jnp.all(fp_canon(a) == fp_canon(b), axis=0)


def fp_is_zero(a: LFp):
    return jnp.all(fp_canon(a) == 0, axis=0)


def fp_pow(a: LFp, e: int) -> LFp:
    """a^e for a static exponent.  The scan carry keeps a stable bound by
    reducing nothing: mont outputs of (reduced x reduced) stay < 2."""
    assert e >= 0
    if e == 0:
        return one_like(a)
    if a.bound > 4.0:
        a = fp_reduce(a)
    # chunked in-kernel chains only on real TPU, and only opt-in until
    # validated on hardware (the relay wedged before the A/B completed;
    # the mont_mul kernel is hardware-proven, the chain variants are
    # interpret-proven): LIGHTHOUSE_TPU_CHAINS=1
    if e > 3 and chains_active():
        from . import pallas_fp

        batch = a.limbs.shape[1:]
        flat = pallas_fp.pow_chain_limbs(a.limbs.reshape(N, -1), e)
        fixp = MAX_MUL_PRODUCT / MONT_DIVISOR + MONT_EPS
        return LFp(flat.reshape((N,) + batch), fixp)
    bits = jnp.array([int(c) for c in bin(e)[2:]], dtype=U32)
    # stabilize the carried bound: sqr of <=4.3 would grow, so pin to the
    # fixpoint bound of mont outputs (range_lint "pow-fix-closure")
    fix = MAX_MUL_PRODUCT / MONT_DIVISOR + MONT_EPS  # 4.3, closed? no:
    # 4.3*4.3 = 18.5 <= 2000 ok, out = 18.5/625+1.1 = 1.13 < 4.3 ✓ and
    # mul with a (<= 4.3): 1.13*4.3 ok, out < 1.11 < 4.3 ✓  => 4.3 is stable.

    def step(acc, bit):
        acc = mont_sqr(acc)
        withmul = mont_mul(acc, a)
        sel = fp_select(bit == 1, withmul, acc)
        return relabel(sel, fix), None

    acc, _ = lax.scan(step, relabel(one_like(a), fix), bits)
    return acc


def fp_inv(a: LFp) -> LFp:
    """Inverse by Fermat: a^(P-2).  a ≡ 0 maps to 0."""
    return fp_pow(a, P_INT - 2)


# ---------------------------------------------------------------------------
# Host codecs
# ---------------------------------------------------------------------------


def encode_mont(xs) -> np.ndarray:
    """Host: ints (standard domain) -> (N, B) canonical Montgomery limbs."""
    return ints_to_limbs([x * R_INT % P_INT for x in xs])


def lfp_encode(xs) -> LFp:
    return LFp(jnp.asarray(encode_mont(xs)), 1.0)


def decode_mont(x) -> list[int]:
    """Host: LFp or raw limb array (any lazy form) -> standard-domain ints."""
    limbs = x.limbs if isinstance(x, LFp) else x
    rinv = pow(R_INT, -1, P_INT)
    return [v * rinv % P_INT for v in limbs_to_ints(np.asarray(limbs))]
