"""Batched BLS12-381 base-field arithmetic in JAX — the TPU limb kernel core.

This is the device analog of blst's C/assembly fp arithmetic (the reference
consumes it via crypto/bls/src/impls/blst.rs); every higher layer of the TPU
backend (Fp2/Fp6/Fp12 tower, curve ops, pairing) is built on the ops here and
is differentially tested against the pure-Python oracle (fields.py).

Representation
--------------
An Fp element is 24 x 16-bit limbs, little-endian, each stored in a uint32
lane: shape ``(24, *batch)`` — the limb axis LEADS so that the trailing batch
axis lands on the TPU's 128-wide vector lanes and every limb op is a full-width
VPU instruction over the batch.  Values are kept canonical (limbs < 2^16,
value < P) in Montgomery form (R = 2^384).

Multiplication is schoolbook over limbs via a Horner scan (MSB-first:
acc = acc * 2^16 + a_i * b), with each 32-bit partial product split into
16-bit halves before accumulation so column sums stay < 2^22 (no overflow in
uint32).  Montgomery reduction is the standard  m = T * P' mod R;
T' = (T + m*P) / R  with one conditional subtraction.

All loops over limbs are ``lax.scan``s so the traced graph stays compact
enough to nest inside the Miller-loop scan.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import params

# ---------------------------------------------------------------------------
# Constants
# ---------------------------------------------------------------------------

BITS = 16
N = 24  # 24 * 16 = 384 bits >= 381
MASK = (1 << BITS) - 1
BASE = 1 << BITS
U32 = jnp.uint32

P_INT = params.P
R_INT = 1 << (BITS * N)  # Montgomery radix 2^384
assert R_INT > P_INT
R1_INT = R_INT % P_INT  # 1 in Montgomery form
R2_INT = R_INT * R_INT % P_INT  # for to-Montgomery conversion
PPRIME_INT = (-pow(P_INT, -1, R_INT)) % R_INT  # -P^-1 mod R


def int_to_limbs(x: int) -> np.ndarray:
    """Host codec: non-negative int < 2^384 -> (N,) uint32 limb vector."""
    assert 0 <= x < R_INT
    return np.array([(x >> (BITS * i)) & MASK for i in range(N)], dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(v) << (BITS * i) for i, v in enumerate(arr))


def ints_to_limbs(xs) -> np.ndarray:
    """Host codec for a batch: list of ints -> (N, len(xs)) uint32."""
    out = np.zeros((N, len(xs)), dtype=np.uint32)
    for j, x in enumerate(xs):
        out[:, j] = int_to_limbs(x)
    return out


def limbs_to_ints(limbs) -> list[int]:
    arr = np.asarray(limbs)
    flat = arr.reshape(N, -1)
    return [limbs_to_int(flat[:, j]) for j in range(flat.shape[1])]


P_LIMBS = jnp.asarray(int_to_limbs(P_INT))
PPRIME_LIMBS = jnp.asarray(int_to_limbs(PPRIME_INT))
ONE_MONT = jnp.asarray(int_to_limbs(R1_INT))
R2_LIMBS = jnp.asarray(int_to_limbs(R2_INT))
ZERO = jnp.zeros((N,), dtype=U32)


def bcast(const, batch_shape) -> jnp.ndarray:
    """Broadcast an (N,) constant to (N, *batch_shape)."""
    return jnp.broadcast_to(
        const.reshape((N,) + (1,) * len(batch_shape)), (N,) + tuple(batch_shape)
    )


def zero_like(a):
    return jnp.zeros_like(a)


def one_like(a):
    return bcast(ONE_MONT, a.shape[1:])


# ---------------------------------------------------------------------------
# Carry / borrow chains (scans over the leading limb axis)
# ---------------------------------------------------------------------------


def carry_chain(cols):
    """Normalize column sums (< 2^31) into canonical limbs; returns
    (limbs, carry_out)."""
    init = jnp.zeros(cols.shape[1:], dtype=U32)

    def step(c, col):
        t = col + c
        return t >> BITS, t & MASK

    carry, limbs = lax.scan(step, init, cols)
    return limbs, carry


def sub_chain(x, y):
    """Limb-wise x - y with borrow; returns (diff mod 2^384, borrow_out)
    where borrow_out is 1 iff x < y."""
    init = jnp.zeros(x.shape[1:], dtype=U32)

    def step(bor, xy):
        x_k, y_k = xy
        t = x_k + U32(BASE) - y_k - bor
        return U32(1) - (t >> BITS), t & MASK

    borrow, limbs = lax.scan(step, init, (x, y))
    return limbs, borrow


def _p_like(a):
    return bcast(P_LIMBS, a.shape[1:])


def cond_sub_p(x):
    """x - P if x >= P else x  (x < 2P)."""
    d, borrow = sub_chain(x, _p_like(x))
    return jnp.where((borrow == 0)[None], d, x)


# ---------------------------------------------------------------------------
# Core field ops
# ---------------------------------------------------------------------------


def fp_add(a, b):
    limbs, carry = carry_chain(a + b)
    del carry  # a + b < 2P < 2^384: no carry out
    return cond_sub_p(limbs)


def fp_sub(a, b):
    d, borrow = sub_chain(a, b)
    # If a < b, add P back (drop the carry: d already wrapped mod 2^384).
    dp, _ = carry_chain(d + _p_like(a))
    return jnp.where((borrow == 1)[None], dp, d)


def fp_neg(a):
    d, _ = sub_chain(_p_like(a), a)
    return jnp.where(fp_is_zero(a)[None], a, d)


def fp_is_zero(a):
    return jnp.all(a == 0, axis=0)


def fp_eq(a, b):
    return jnp.all(a == b, axis=0)


def fp_select(mask, a, b):
    """mask over batch shape: a where mask else b."""
    return jnp.where(mask[None], a, b)


def mul_wide(a, b):
    """Full 48-limb product of two canonical 24-limb numbers (normalized)."""
    nb = a.shape[1:]
    acc0 = jnp.zeros((2 * N,) + nb, dtype=U32)

    def step(acc, a_i):
        p = a_i[None] * b
        plo = p & MASK
        phi = p >> BITS
        acc = jnp.concatenate([jnp.zeros_like(acc[:1]), acc[:-1]], axis=0)
        acc = acc.at[:N].add(plo)
        acc = acc.at[1 : N + 1].add(phi)
        return acc, None

    acc, _ = lax.scan(step, acc0, jnp.flip(a, 0))
    limbs, carry = carry_chain(acc)
    del carry  # product < 2^768
    return limbs


def mul_low(a, b):
    """Low 24 limbs of a*b, i.e. a*b mod 2^384 (normalized)."""
    nb = a.shape[1:]
    acc0 = jnp.zeros((N,) + nb, dtype=U32)

    def step(acc, a_i):
        p = a_i[None] * b
        plo = p & MASK
        phi = p >> BITS
        acc = jnp.concatenate([jnp.zeros_like(acc[:1]), acc[:-1]], axis=0)
        acc = acc + plo
        acc = acc.at[1:].add(phi[:-1])
        return acc, None

    acc, _ = lax.scan(step, acc0, jnp.flip(a, 0))
    limbs, _ = carry_chain(acc)  # carries out of limb 23 are dropped (mod R)
    return limbs


def mont_mul(a, b):
    """Montgomery product  a * b * R^-1 mod P  (canonical in, canonical out)."""
    t = mul_wide(a, b)
    m = mul_low(t[:N], bcast(PPRIME_LIMBS, a.shape[1:]))
    u = mul_wide(m, _p_like(a))
    s, carry = carry_chain(t + u)
    del carry  # t + u < 2^768 for canonical inputs
    return cond_sub_p(s[N:])


def mont_sqr(a):
    return mont_mul(a, a)


def fp_dbl(a):
    return fp_add(a, a)


def to_mont(a):
    """Standard-domain limbs -> Montgomery domain (device)."""
    return mont_mul(a, bcast(R2_LIMBS, a.shape[1:]))


def from_mont(a):
    """Montgomery -> standard domain: mont_mul(a, 1)."""
    return mont_mul(a, one_std_like(a))


def one_std_like(a):
    one = np.zeros((N,), dtype=np.uint32)
    one[0] = 1
    return bcast(jnp.asarray(one), a.shape[1:])


def fp_pow(a, e: int):
    """a^e for a static exponent (square-and-multiply scan over e's bits)."""
    assert e >= 0
    if e == 0:
        return one_like(a)
    bits = jnp.array([int(c) for c in bin(e)[2:]], dtype=U32)

    def step(acc, bit):
        acc = mont_sqr(acc)
        withmul = mont_mul(acc, a)
        return jnp.where((bit == 1), withmul, acc), None

    # MSB-first from acc = 1: first iteration yields a itself.
    acc, _ = lax.scan(step, one_like(a), bits)
    return acc


def fp_inv(a):
    """Inverse by Fermat: a^(P-2).  a == 0 maps to 0."""
    return fp_pow(a, P_INT - 2)


# ---------------------------------------------------------------------------
# Host helpers: Montgomery-domain codecs
# ---------------------------------------------------------------------------


def encode_mont(xs) -> np.ndarray:
    """Host: list of ints (standard domain) -> (N, B) Montgomery limbs."""
    return ints_to_limbs([x * R_INT % P_INT for x in xs])


def decode_mont(limbs) -> list[int]:
    """Host: (N, ...) Montgomery limbs -> standard-domain ints."""
    rinv = pow(R_INT, -1, P_INT)
    return [x * rinv % P_INT for x in limbs_to_ints(limbs)]
