"""JAX/TPU BLS12-381 backend: limb-vectorized field, curve, and pairing
kernels plus the "jax" verification backend (backend.py).

Importing this package requires jax; the api registry loads it lazily via
``set_backend("jax")``.
"""

from .backend import JaxBackend, register

__all__ = ["JaxBackend", "register"]
