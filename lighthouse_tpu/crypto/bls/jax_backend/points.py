"""Branchless Jacobian point arithmetic over Fp (G1) and Fp2 (G2) in JAX.

Device analog of blst's point ops as used by verify_signature_sets
(reference: crypto/bls/src/impls/blst.rs:71-117): doubling, addition,
batched 64-bit scalar multiplication (the random batch weights, RAND_BITS=64
at blst.rs:14), the psi endomorphism and Scott's fast G2 subgroup test
(constants from endo.py, derived + self-checked there).

A point is a pytree ``(X, Y, Z, inf)``: Jacobian coordinates (x = X/Z^2,
y = Y/Z^3) in the lazy LFp representation plus an explicit boolean infinity
flag.  The flag — rather than a Z ≡ 0 (mod P) test, which would cost a
canonicalization in the lazy representation — makes infinity handling free
inside scan bodies.

Two additions:

* ``jac_add`` — complete: detects doubling (P+P) and cancellation (P-P) via
  canonical equality and handles infinities; use anywhere inputs may
  coincide (batch accumulation of adversarial points, tree reductions).
* ``jac_add_fast`` — no coincidence detection; only infinity flags.  Valid
  when operands cannot be equal or opposite: inside double-and-add scalar
  multiplication with a prime-order base and scalar < order, the running
  accumulator is [k]Q with 2 <= k < order, never ±Q.  This is the hot-loop
  add.

Every point-producing op ends by reducing its coordinates (stacked) so scan
carries have stable static bounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import endo as _endo
from .. import params
from . import fp as F
from . import tower as T

# ---------------------------------------------------------------------------
# Field-op namespaces so G1/G2 share one implementation
# ---------------------------------------------------------------------------


class _FpOps:
    add = staticmethod(F.fp_add)
    sub = staticmethod(F.fp_sub)
    neg = staticmethod(F.fp_neg)
    mul = staticmethod(F.mont_mul)
    sqr = staticmethod(F.mont_sqr)
    mul_many = staticmethod(T.mm_many)
    select = staticmethod(F.fp_select)
    eq = staticmethod(F.fp_eq)
    is_zero = staticmethod(F.fp_is_zero)
    zero_like = staticmethod(F.zero_like)
    one_like = staticmethod(F.one_like)
    reduce_many = staticmethod(T.reduce_many)
    ncoords = 1  # lanes per field element when stacking

    @staticmethod
    def dbl(a):
        return F.fp_add(a, a)

    @staticmethod
    def lanes(a):
        return [a]

    @staticmethod
    def unlanes(lanes):
        return lanes[0]

    @staticmethod
    def batch_shape(a):
        return F.batch_shape(a)


class _Fp2Ops:
    add = staticmethod(T.fp2_add)
    sub = staticmethod(T.fp2_sub)
    neg = staticmethod(T.fp2_neg)
    mul = staticmethod(T.fp2_mul)
    sqr = staticmethod(T.fp2_sqr)
    mul_many = staticmethod(T.fp2_mul_many)
    select = staticmethod(T.fp2_select)
    eq = staticmethod(T.fp2_eq)
    is_zero = staticmethod(T.fp2_is_zero)
    zero_like = staticmethod(T.fp2_zero_like)
    one_like = staticmethod(T.fp2_one_like)
    dbl = staticmethod(T.fp2_dbl)
    ncoords = 2

    @staticmethod
    def reduce_many(xs):
        return T.reduce_many(xs)

    @staticmethod
    def lanes(a):
        return [a[0], a[1]]

    @staticmethod
    def unlanes(lanes):
        return (lanes[0], lanes[1])

    @staticmethod
    def batch_shape(a):
        return F.batch_shape(a[0])


FP_OPS = _FpOps
FP2_OPS = _Fp2Ops


def _reduce_coords(ops, coords):
    """Stacked reduction of a list of field elements to stable bound 2."""
    lanes = []
    for c in coords:
        lanes += ops.lanes(c)
    red = ops.reduce_many(lanes)
    out = []
    n = ops.ncoords
    for i in range(len(coords)):
        out.append(ops.unlanes(red[i * n : (i + 1) * n]))
    return out


def pt_select(ops, mask, p, q):
    out = tuple(ops.select(mask, a, b) for a, b in zip(p[:3], q[:3]))
    return out + (jnp.where(mask, p[3], q[3]),)


def pt_infinity_like(ops, p):
    one = ops.one_like(p[0])
    bshape = ops.batch_shape(p[0])
    return (one, one, ops.zero_like(p[0]), jnp.ones(bshape, dtype=bool))


def pt_is_infinity(ops, p):
    return p[3]


def from_affine(ops, xy):
    x, y = xy
    bshape = ops.batch_shape(x)
    return (x, y, ops.one_like(x), jnp.zeros(bshape, dtype=bool))


def pt_neg(ops, p):
    return (p[0], ops.neg(p[1]), p[2], p[3])


def jac_double(ops, p):
    """2P, a = 0 curve.  Valid for non-infinity inputs of odd order (no
    y = 0 points); infinity propagates via the flag (coords are garbage
    under the flag, as everywhere)."""
    X, Y, Z = p[0], p[1], p[2]
    A, B, YZ = ops.mul_many([X, Y, Y], [X, Y, Z])
    E = ops.add(ops.dbl(A), A)
    XB = ops.add(X, B)
    C, t, Fv = ops.mul_many([B, XB, E], [B, XB, E])
    D = ops.dbl(ops.sub(ops.sub(t, A), C))
    X3 = ops.sub(Fv, ops.dbl(D))
    (m,) = ops.mul_many([E], [ops.sub(D, X3)])
    C8 = ops.dbl(ops.dbl(ops.dbl(C)))
    Y3 = ops.sub(m, C8)
    Z3 = ops.dbl(YZ)
    X3, Y3, Z3 = _reduce_coords(ops, [X3, Y3, Z3])
    return (X3, Y3, Z3, p[3])


def _raw_add(ops, p1, p2):
    """Core Jacobian addition; undefined when P1 = ±P2 or an input is
    infinity.  Returns reduced coordinates."""
    X1, Y1, Z1 = p1[0], p1[1], p1[2]
    X2, Y2, Z2 = p2[0], p2[1], p2[2]
    Z1Z1, Z2Z2 = ops.mul_many([Z1, Z2], [Z1, Z2])
    U1, U2, t1, t2 = ops.mul_many([X1, X2, Y1, Y2], [Z2Z2, Z1Z1, Z2, Z1])
    S1, S2 = ops.mul_many([t1, t2], [Z2Z2, Z1Z1])
    H = ops.sub(U2, U1)
    rr = ops.dbl(ops.sub(S2, S1))
    H2 = ops.dbl(H)
    Zs = ops.add(Z1, Z2)
    I, rr2, W = ops.mul_many([H2, rr, Zs], [H2, rr, Zs])
    J, V = ops.mul_many([H, U1], [I, I])
    X3 = ops.sub(ops.sub(rr2, J), ops.dbl(V))
    m1, m2, Z3 = ops.mul_many(
        [rr, S1, ops.sub(ops.sub(W, Z1Z1), Z2Z2)],
        [ops.sub(V, X3), J, H],
    )
    Y3 = ops.sub(m1, ops.dbl(m2))
    X3, Y3, Z3 = _reduce_coords(ops, [X3, Y3, Z3])
    return (X3, Y3, Z3), (U1, U2, S1, S2)


def jac_add_fast(ops, p1, p2):
    """P1 + P2 without coincidence detection (see module docstring for the
    validity condition).  Infinity handled via flags only."""
    (X3, Y3, Z3), _ = _raw_add(ops, p1, p2)
    inf1, inf2 = p1[3], p2[3]
    out = (X3, Y3, Z3, inf1 & inf2)
    out = pt_select(ops, inf2, (p1[0], p1[1], p1[2], inf1 & inf2), out)
    out = pt_select(ops, inf1, (p2[0], p2[1], p2[2], inf1 & inf2), out)
    return out


def jac_add(ops, p1, p2):
    """Complete P1 + P2: doubling, cancellation, and infinity via selects."""
    (X3, Y3, Z3), (U1, U2, S1, S2) = _raw_add(ops, p1, p2)
    inf1, inf2 = p1[3], p2[3]
    both_finite = jnp.logical_not(inf1 | inf2)
    ex = ops.eq(U1, U2)
    ey = ops.eq(S1, S2)
    is_dbl = ex & ey & both_finite
    cancels = ex & jnp.logical_not(ey) & both_finite
    inf_out = (inf1 & inf2) | cancels
    out = (X3, Y3, Z3, inf_out)
    dblp = jac_double(ops, p1)
    out = pt_select(ops, is_dbl, (dblp[0], dblp[1], dblp[2], inf_out), out)
    out = pt_select(ops, inf2, (p1[0], p1[1], p1[2], inf_out), out)
    out = pt_select(ops, inf1, (p2[0], p2[1], p2[2], inf_out), out)
    return out


def jac_eq(ops, p1, p2):
    """Equality including infinity, via cross-multiplication."""
    X1, Y1, Z1 = p1[0], p1[1], p1[2]
    X2, Y2, Z2 = p2[0], p2[1], p2[2]
    Z1Z1, Z2Z2, t1, t2 = ops.mul_many([Z1, Z2, Y1, Y2], [Z1, Z2, Z2, Z1])
    a, b, c, d = ops.mul_many([X1, X2, t1, t2], [Z2Z2, Z1Z1, Z2Z2, Z1Z1])
    ex = ops.eq(a, b)
    ey = ops.eq(c, d)
    inf1, inf2 = p1[3], p2[3]
    return (inf1 & inf2) | (jnp.logical_not(inf1 | inf2) & ex & ey)


def pt_relabel(ops, p, bound: float):
    """Pin coordinate bounds (upward) for scan-carry stability."""

    def up(c):
        if isinstance(c, F.LFp):
            return F.relabel(c, bound)
        return tuple(up(x) for x in c)

    return tuple(up(c) for c in p[:3]) + (p[3],)


def scalar_mul_bits(ops, p, bits):
    """[k]P with per-element scalars given as bits (nbits, *batch), MSB
    first.  Double-and-always-add with select; uses the fast add (valid:
    p has prime order r and k < 2^64 << r, so the accumulator never
    coincides with ±p)."""
    p = pt_relabel(ops, p, 2.0)

    def step(acc, bit):
        acc = jac_double(ops, acc)
        added = jac_add_fast(ops, acc, p)
        return pt_select(ops, bit == 1, added, acc), None

    acc, _ = lax.scan(step, pt_relabel(ops, pt_infinity_like(ops, p), 2.0), bits)
    return acc


def scalar_mul_const(ops, p, k: int):
    """[k]P for a static scalar; negative k negates the point."""
    if k < 0:
        return scalar_mul_const(ops, pt_neg(ops, p), -k)
    if k == 0:
        return pt_infinity_like(ops, p)
    bshape = ops.batch_shape(p[0])
    nbits = [int(c) for c in bin(k)[2:]]
    bits = jnp.broadcast_to(
        jnp.array(nbits, dtype=jnp.uint32).reshape((len(nbits),) + (1,) * len(bshape)),
        (len(nbits),) + tuple(bshape),
    )
    return scalar_mul_bits(ops, p, bits)


def to_affine(ops, p, inv_fn):
    """Jacobian -> affine (x, y); where the infinity flag is set the output
    coords are garbage — callers must consult pt_is_infinity."""
    X, Y, Z = p[0], p[1], p[2]
    zinv = inv_fn(Z)
    zinv2 = ops.sqr(zinv)
    (x,) = ops.mul_many([X], [zinv2])
    (y,) = ops.mul_many([ops.mul(Y, zinv2)], [zinv])
    return (x, y)


# ---------------------------------------------------------------------------
# G2 endomorphism + fast subgroup check (constants from endo.py)
# ---------------------------------------------------------------------------


def _psi_consts(bshape):
    cx = T.fp2_const(_endo.PSI_CX, bshape)
    cy = T.fp2_const(_endo.PSI_CY, bshape)
    return cx, cy


def psi_affine(xy):
    """psi on an affine G2 point pytree ((xc0,xc1),(yc0,yc1))."""
    x, y = xy
    bshape = F.batch_shape(x[0])
    cx, cy = _psi_consts(bshape)
    px, py = T.fp2_mul_many([T.fp2_conj(x), T.fp2_conj(y)], [cx, cy])
    return (px, py)


_X_ABS_BITS = [int(c) for c in bin(abs(params.X))[2:]]


def g2_subgroup_check(xy):
    """Scott's test:  Q in G2  iff  psi(Q) == [x]Q  (x < 0: compare with
    the negated |x| multiple).  Batched over trailing dims; returns bools.
    Inputs must be valid curve points (deserialization enforces on-curve)."""
    x, _y = xy
    bshape = F.batch_shape(x[0])
    Q = from_affine(FP2_OPS, xy)
    bits = jnp.broadcast_to(
        jnp.array(_X_ABS_BITS, dtype=jnp.uint32).reshape(
            (len(_X_ABS_BITS),) + (1,) * len(bshape)
        ),
        (len(_X_ABS_BITS),) + tuple(bshape),
    )
    xQ = scalar_mul_bits(FP2_OPS, Q, bits)  # [|x|]Q
    psiQ = from_affine(FP2_OPS, psi_affine(xy))
    return jac_eq(FP2_OPS, psiQ, pt_neg(FP2_OPS, xQ))


# ---------------------------------------------------------------------------
# Host codecs: oracle affine points <-> device arrays
# ---------------------------------------------------------------------------


def g1_encode(points) -> tuple:
    """Host: list of oracle affine G1 points (no infinities) -> affine
    device pytree (x, y)."""
    xs = [p[0].v for p in points]
    ys = [p[1].v for p in points]
    return (F.lfp_encode(xs), F.lfp_encode(ys))


def g2_encode(points) -> tuple:
    x = T.fp2_encode([p[0] for p in points])
    y = T.fp2_encode([p[1] for p in points])
    return (x, y)


def g1_decode_jac(p) -> list:
    """Device Jacobian G1 (X, Y, Z, inf) -> oracle affine points
    (None for infinity)."""
    from .. import curve as C
    from .. import fields as O

    X = F.decode_mont(p[0])
    Y = F.decode_mont(p[1])
    Z = F.decode_mont(p[2])
    inf = np.asarray(p[3]).reshape(-1)
    out = []
    for x, y, z, isinf in zip(X, Y, Z, inf):
        if isinf or z == 0:
            out.append(None)
        else:
            out.append(C.from_jacobian((O.Fp(x), O.Fp(y), O.Fp(z)), O.Fp))
    return out


def g2_decode_jac(p) -> list:
    from .. import curve as C
    from .. import fields as O

    Xs = T.fp2_decode(p[0])
    Ys = T.fp2_decode(p[1])
    Zs = T.fp2_decode(p[2])
    inf = np.asarray(p[3]).reshape(-1)
    out = []
    for x, y, z, isinf in zip(Xs, Ys, Zs, inf):
        if isinf or z.is_zero():
            out.append(None)
        else:
            out.append(C.from_jacobian((x, y, z), O.Fp2))
    return out
