"""Branchless Jacobian point arithmetic over Fp (G1) and Fp2 (G2) in JAX.

Device analog of blst's point ops as used by verify_signature_sets
(reference: crypto/bls/src/impls/blst.rs:71-117): doubling, complete-ish
addition via select, batched 64-bit scalar multiplication (the random batch
weights, RAND_BITS=64 at blst.rs:14), the psi endomorphism and Scott's fast
G2 subgroup test (constants from endo.py, derived + self-checked there).

A point is a pytree (X, Y, Z) of field elements (Jacobian; x = X/Z^2,
y = Y/Z^3); infinity iff Z == 0.  All case splits (infinity operands,
doubling) are jnp.where selects, so every op is jit/scan-safe with static
shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import endo as _endo
from .. import params
from . import fp as F
from . import tower as T

# ---------------------------------------------------------------------------
# Field-op namespaces so G1/G2 share one implementation
# ---------------------------------------------------------------------------


class _FpOps:
    add = staticmethod(F.fp_add)
    sub = staticmethod(F.fp_sub)
    neg = staticmethod(F.fp_neg)
    mul = staticmethod(F.mont_mul)
    sqr = staticmethod(F.mont_sqr)
    select = staticmethod(F.fp_select)
    eq = staticmethod(F.fp_eq)
    is_zero = staticmethod(F.fp_is_zero)
    zero_like = staticmethod(F.zero_like)
    one_like = staticmethod(F.one_like)

    @staticmethod
    def dbl(a):
        return F.fp_add(a, a)


class _Fp2Ops:
    add = staticmethod(T.fp2_add)
    sub = staticmethod(T.fp2_sub)
    neg = staticmethod(T.fp2_neg)
    mul = staticmethod(T.fp2_mul)
    sqr = staticmethod(T.fp2_sqr)
    select = staticmethod(T.fp2_select)
    eq = staticmethod(T.fp2_eq)
    is_zero = staticmethod(T.fp2_is_zero)
    zero_like = staticmethod(T.fp2_zero_like)
    one_like = staticmethod(T.fp2_one_like)
    dbl = staticmethod(T.fp2_dbl)


FP_OPS = _FpOps
FP2_OPS = _Fp2Ops


def pt_select(ops, mask, p, q):
    return tuple(ops.select(mask, a, b) for a, b in zip(p, q))


def pt_infinity_like(ops, p):
    one = ops.one_like(p[0])
    return (one, one, ops.zero_like(p[0]))


def pt_is_infinity(ops, p):
    return ops.is_zero(p[2])


def from_affine(ops, xy):
    x, y = xy
    return (x, y, ops.one_like(x))


def pt_neg(ops, p):
    return (p[0], ops.neg(p[1]), p[2])


def jac_double(ops, p):
    """2P, a = 0 curve.  Infinity and Y=0 fall out naturally (Z3 = 2YZ)."""
    X, Y, Z = p
    A = ops.sqr(X)
    B = ops.sqr(Y)
    C = ops.sqr(B)
    t = ops.sub(ops.sub(ops.sqr(ops.add(X, B)), A), C)
    D = ops.dbl(t)
    E = ops.add(ops.dbl(A), A)
    Fv = ops.sqr(E)
    X3 = ops.sub(Fv, ops.dbl(D))
    C8 = ops.dbl(ops.dbl(ops.dbl(C)))
    Y3 = ops.sub(ops.mul(E, ops.sub(D, X3)), C8)
    Z3 = ops.dbl(ops.mul(Y, Z))
    return (X3, Y3, Z3)


def jac_add(ops, p1, p2):
    """P1 + P2, complete via selects (handles infinity and doubling)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = ops.sqr(Z1)
    Z2Z2 = ops.sqr(Z2)
    U1 = ops.mul(X1, Z2Z2)
    U2 = ops.mul(X2, Z1Z1)
    S1 = ops.mul(ops.mul(Y1, Z2), Z2Z2)
    S2 = ops.mul(ops.mul(Y2, Z1), Z1Z1)
    H = ops.sub(U2, U1)
    rr = ops.dbl(ops.sub(S2, S1))
    I = ops.sqr(ops.dbl(H))
    J = ops.mul(H, I)
    V = ops.mul(U1, I)
    X3 = ops.sub(ops.sub(ops.sqr(rr), J), ops.dbl(V))
    Y3 = ops.sub(ops.mul(rr, ops.sub(V, X3)), ops.dbl(ops.mul(S1, J)))
    Z3 = ops.mul(
        ops.sub(ops.sub(ops.sqr(ops.add(Z1, Z2)), Z1Z1), Z2Z2), H
    )
    added = (X3, Y3, Z3)
    # H == 0, rr != 0  => opposite points => Z3 = ...*H = 0: already infinity.
    inf1 = pt_is_infinity(ops, p1)
    inf2 = pt_is_infinity(ops, p2)
    is_dbl = (
        ops.eq(U1, U2) & ops.eq(S1, S2) & jnp.logical_not(inf1 | inf2)
    )
    out = pt_select(ops, is_dbl, jac_double(ops, p1), added)
    out = pt_select(ops, inf2, p1, out)
    out = pt_select(ops, inf1, p2, out)
    return out


def jac_eq(ops, p1, p2):
    """Equality including infinity, via cross-multiplication."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = ops.sqr(Z1)
    Z2Z2 = ops.sqr(Z2)
    ex = ops.eq(ops.mul(X1, Z2Z2), ops.mul(X2, Z1Z1))
    ey = ops.eq(
        ops.mul(ops.mul(Y1, Z2), Z2Z2), ops.mul(ops.mul(Y2, Z1), Z1Z1)
    )
    inf1 = pt_is_infinity(ops, p1)
    inf2 = pt_is_infinity(ops, p2)
    return (inf1 & inf2) | (jnp.logical_not(inf1 | inf2) & ex & ey)


def scalar_mul_bits(ops, p, bits):
    """[k]P with per-element scalars given as bits (nbits, *batch), MSB first.

    Double-and-always-add with select — branchless, constant two field-mul
    cost per bit; used for the 64-bit random batch weights.
    """

    def step(acc, bit):
        acc = jac_double(ops, acc)
        added = jac_add(ops, acc, p)
        return pt_select(ops, bit == 1, added, acc), None

    acc, _ = lax.scan(step, pt_infinity_like(ops, p), bits)
    return acc


def scalar_mul_const(ops, p, k: int):
    """[k]P for a static scalar; negative k negates the point."""
    if k < 0:
        return scalar_mul_const(ops, pt_neg(ops, p), -k)
    if k == 0:
        return pt_infinity_like(ops, p)
    bshape = p[2].shape[1:] if isinstance(p[2], jnp.ndarray) else p[2][0].shape[1:]
    nbits = [int(c) for c in bin(k)[2:]]
    bits = jnp.broadcast_to(
        jnp.array(nbits, dtype=jnp.uint32).reshape((len(nbits),) + (1,) * len(bshape)),
        (len(nbits),) + tuple(bshape),
    )
    return scalar_mul_bits(ops, p, bits)


def to_affine(ops, p, inv_fn):
    """Jacobian -> affine (x, y); infinity maps to (0, 0) — callers must
    handle it via pt_is_infinity.  inv_fn is the field inversion."""
    X, Y, Z = p
    zinv = inv_fn(Z)
    zinv2 = ops.sqr(zinv)
    return (ops.mul(X, zinv2), ops.mul(ops.mul(Y, zinv2), zinv))


# ---------------------------------------------------------------------------
# G2 endomorphism + fast subgroup check (constants from endo.py)
# ---------------------------------------------------------------------------


def _psi_consts(bshape):
    cx = T.fp2_const(_endo.PSI_CX, bshape)
    cy = T.fp2_const(_endo.PSI_CY, bshape)
    return cx, cy


def psi_affine(xy):
    """psi on an affine G2 point pytree ((xc0,xc1),(yc0,yc1))."""
    x, y = xy
    bshape = x[0].shape[1:]
    cx, cy = _psi_consts(bshape)
    return (T.fp2_mul(T.fp2_conj(x), cx), T.fp2_mul(T.fp2_conj(y), cy))


_X_ABS_BITS = [int(c) for c in bin(abs(params.X))[2:]]


def g2_subgroup_check(xy):
    """Scott's test:  Q in G2  iff  psi(Q) == [x]Q  (x < 0: compare with
    the negated |x| multiple).  Batched over trailing dims; returns bools."""
    x, _y = xy
    bshape = x[0].shape[1:]
    Q = from_affine(FP2_OPS, xy)
    bits = jnp.broadcast_to(
        jnp.array(_X_ABS_BITS, dtype=jnp.uint32).reshape(
            (len(_X_ABS_BITS),) + (1,) * len(bshape)
        ),
        (len(_X_ABS_BITS),) + tuple(bshape),
    )
    xQ = scalar_mul_bits(FP2_OPS, Q, bits)  # [|x|]Q
    psiQ = from_affine(FP2_OPS, psi_affine(xy))
    return jac_eq(FP2_OPS, psiQ, pt_neg(FP2_OPS, xQ))


# ---------------------------------------------------------------------------
# Host codecs: oracle affine points <-> device arrays
# ---------------------------------------------------------------------------


def g1_encode(points) -> tuple:
    """Host: list of oracle affine G1 points (no infinities) -> device pytree."""
    xs = [p[0].v for p in points]
    ys = [p[1].v for p in points]
    return (jnp.asarray(F.encode_mont(xs)), jnp.asarray(F.encode_mont(ys)))


def g2_encode(points) -> tuple:
    from .. import fields as O

    x = T.fp2_encode([p[0] for p in points])
    y = T.fp2_encode([p[1] for p in points])
    return (x, y)


def g1_decode_jac(p) -> list:
    """Device Jacobian G1 -> oracle affine points (None for infinity)."""
    from .. import curve as C
    from .. import fields as O

    X = F.decode_mont(np.asarray(p[0]))
    Y = F.decode_mont(np.asarray(p[1]))
    Z = F.decode_mont(np.asarray(p[2]))
    out = []
    for x, y, z in zip(X, Y, Z):
        if z == 0:
            out.append(None)
        else:
            jac = (O.Fp(x), O.Fp(y), O.Fp(z))
            out.append(C.from_jacobian(jac, O.Fp))
    return out


def g2_decode_jac(p) -> list:
    from .. import curve as C
    from .. import fields as O

    Xs = T.fp2_decode(p[0])
    Ys = T.fp2_decode(p[1])
    Zs = T.fp2_decode(p[2])
    out = []
    for x, y, z in zip(Xs, Ys, Zs):
        if z.is_zero():
            out.append(None)
        else:
            out.append(C.from_jacobian((x, y, z), O.Fp2))
    return out
