"""Width-parameterized limb representations for the BLS12-381 base field.

Historically the limb width (15 bits x 26 limbs) was hard-coded across
fp.py / pallas_fp.py; the MXU remap (pallas_mxu.py) needs a second split
— 13-bit limbs, the widest int32-safe width per RANGE_REPORT.json's
``mxu`` budget table — so the width becomes a first-class parameter
here.

The load-bearing identity: **26 x 15 = 390 = 30 x 13**, so both splits
share the Montgomery radix R = 2^390.  The Montgomery domain, R1 (one in
Montgomery form), R2, and P' = -P^-1 mod R are literally the *same
integers* under both widths; switching splits is pure limb regrouping —
no domain conversion, no extra Montgomery multiplies at the boundary.

The 13-bit *plane* carries 31 limbs, one more than the 30 that span R:
quasi-normalized 15-bit inputs (limbs <= fp.QMAX = 32896) encode values
up to ~(1 + 2^-15) * 2^390, i.e. just over 390 bits, and the top
conversion chunk of limb 25 (bit position 375, offset 11 inside 13-bit
column 28) spills into column 30.  31 x 13 = 403 bits covers it; the
column budget 31 * QMAX13^2 = 2,081,390,716 < 2^31 still fits the int32
MXU accumulator with ~3.1% margin (machine-checked by
analysis/range_lint's mxu report).

Everything here is host-side numpy/int — the device kernels
(pallas_fp.py, pallas_mxu.py) bake these constants in as numpy arrays.
Exactness of the derivations is asserted at import time from first
principles (no dependence on fp.py; tests cross-check the two).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from .. import params

P_INT = params.P

R_BITS = 390  # = 26*15 = 30*13: the shared Montgomery radix exponent
R_INT = 1 << R_BITS
R1_INT = R_INT % P_INT  # 1 in Montgomery form
R2_INT = R_INT * R_INT % P_INT
PPRIME_INT = (-pow(P_INT, -1, R_INT)) % R_INT  # -P^-1 mod R

# Derivation checks, exact integer arithmetic (the "re-derived Montgomery
# constants" contract: these must hold for ANY split sharing R = 2^390).
assert R_INT > 512 * P_INT  # bound-tracking headroom (fp.MAX_BOUND)
assert (PPRIME_INT * P_INT) % R_INT == R_INT - 1  # P*P' == -1 mod R
assert (R1_INT - R_INT) % P_INT == 0 and 0 <= R1_INT < P_INT
assert (R2_INT - R_INT * R_INT) % P_INT == 0 and 0 <= R2_INT < P_INT


@dataclasses.dataclass(frozen=True)
class LimbSpec:
    """A little-endian base-2^bits limb plane for field values.

    ``n`` is the plane height (limb count); it may exceed the
    ``radix_limbs`` that span R when quasi-normalized values can
    overshoot 2^390 (the 13-bit plane).  ``qmax`` is the quasi-
    normalized per-limb bound the kernels are proven against.
    """

    bits: int
    n: int
    qmax: int

    def __post_init__(self):
        assert R_BITS % self.bits == 0, "split must divide the radix"
        assert self.n >= self.radix_limbs
        assert self.qmax > self.mask, "qmax must admit strict limbs"

    @property
    def mask(self) -> int:
        return (1 << self.bits) - 1

    @property
    def radix_limbs(self) -> int:
        """Limbs spanning exactly R = 2^390 (carry-chain truncation point)."""
        return R_BITS // self.bits

    @property
    def span_bits(self) -> int:
        return self.bits * self.n

    # -- codecs ------------------------------------------------------------

    def int_to_limbs(self, x: int) -> np.ndarray:
        """Non-negative int < 2^span_bits -> (n,) uint32 strict limbs."""
        assert 0 <= x < (1 << self.span_bits)
        return np.array(
            [(x >> (self.bits * i)) & self.mask for i in range(self.n)],
            dtype=np.uint32,
        )

    def limbs_to_int(self, limbs) -> int:
        arr = np.asarray(limbs, dtype=np.uint64)
        assert arr.shape[0] == self.n
        return sum(int(v) << (self.bits * i) for i, v in enumerate(arr))

    def limbs_to_ints(self, limbs) -> list:
        arr = np.asarray(limbs)
        flat = arr.reshape(self.n, -1)
        return [self.limbs_to_int(flat[:, j]) for j in range(flat.shape[1])]

    # -- per-width Montgomery constants ------------------------------------

    @functools.cached_property
    def p_limbs(self) -> np.ndarray:
        return self.int_to_limbs(P_INT)

    @functools.cached_property
    def pprime_limbs(self) -> np.ndarray:
        return self.int_to_limbs(PPRIME_INT)

    @functools.cached_property
    def r1_limbs(self) -> np.ndarray:
        return self.int_to_limbs(R1_INT)


# The production 15-bit split (fp.py's native plane).
SPEC15 = LimbSpec(bits=15, n=26, qmax=(1 << 15) + (1 << 7))

# The MXU 13-bit split: widest int32-safe width (RANGE_REPORT mxu table),
# 31-limb plane (see module docstring), qmax chosen one over the proven
# device bounds (_to13 emits <= 8193; compressed dot columns <= 8192).
QMAX13 = (1 << 13) + 2
SPEC13 = LimbSpec(bits=13, n=31, qmax=QMAX13)

# The int32 accumulator budget that makes 13 bits the widest safe split:
# every schoolbook column is a sum of <= 31 products of quasi limbs.
assert SPEC13.n * QMAX13 * QMAX13 < 1 << 31
# ...and 14 bits is not, even at strict limbs (ceil(381/14) = 28 limbs):
assert 28 * ((1 << 14) - 1) ** 2 >= 1 << 31

# Named-plane registry: the kernel-arm table (autotune.ARM_TABLE) binds
# each arm to a LimbSpec by NAME so the tune-plan lint can cross-check
# the binding without importing jax.  A future plane (e.g. the
# RANGE_REPORT-proven 43×9-bit f32 split — note 9 ∤ 390, so it needs a
# relaxed radix contract before it can be a LimbSpec) registers here and
# in ARM_TABLE, nowhere else.
SPECS: dict[str, LimbSpec] = {
    "SPEC15": SPEC15,
    "SPEC13": SPEC13,
}


def convert(limbs, src: LimbSpec, dst: LimbSpec) -> np.ndarray:
    """Exact value-preserving re-limb (host reference codec).

    Accepts quasi-normalized input (any uint32 limbs); output is strict
    in ``dst``.  The device converters in pallas_mxu.py are differential-
    tested against this.
    """
    arr = np.asarray(limbs)
    flat = arr.reshape(src.n, -1)
    out = np.empty((dst.n, flat.shape[1]), dtype=np.uint32)
    for j in range(flat.shape[1]):
        v = sum(int(x) << (src.bits * i) for i, x in enumerate(flat[:, j]))
        out[:, j] = dst.int_to_limbs(v)
    return out.reshape((dst.n,) + arr.shape[1:])
