"""Multi-chip batch verification: data-parallel sharding over a device mesh.

The reference scales batch BLS verification across CPU cores with rayon
chunking (consensus/state_processing/src/per_block_processing/
block_signature_verifier.rs:396-405: sets/threads chunks, AND-reduce).  The
TPU analog shards the signature-set batch across the mesh's data axis with
``shard_map``: every device runs subgroup checks, weight scalar muls, and
Miller loops for its local shard; the tiny combine — the GT partial products
(one Fp12 per device) and the local signature accumulators (one G2 point per
device) — crosses ICI via all_gather, and the single final exponentiation is
computed replicated.  The GT accumulation is associative, exactly the
property SURVEY.md §2.8 calls out for mesh reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PS

from ....parallel.mesh import (
    allgather_tree,
    and_reduce,
    batch_spec,
    compat_shard_map,
    ring_reduce,
)
from . import fp as F
from . import pairing as PR
from . import points as P
from . import tower as T
from .backend import _neg_gen_const, _tree_reduce_g2

# The version shim lives in parallel/mesh.py now so the rule-driven
# sharded program (parallel/partition.py) and these kernels share one
# guard; the old private name stays importable for external callers.
_shard_map = compat_shard_map


def _trailing_extent(tree) -> int:
    """Trailing-axis extent of the first leaf — the global batch size."""
    return int(jax.tree.leaves(tree)[0].shape[-1])


def _pad_tail_cols(tree, pad: int):
    """Append ``pad`` copies of column 0 to every leaf's trailing axis.

    Column 0 is an arbitrary *real* entry, so the padding is well-formed
    field data; whether it is verdict-neutral depends on the kernel's
    combine — AND-reduce kernels tolerate it as-is (a duplicate of a
    valid set stays valid; of an invalid set, the verdict was already
    False), product-combine kernels must additionally mask the padded
    lanes out (see make_pair_sharded_aggregate_verify).
    """
    if pad <= 0:
        return tree
    return jax.tree.map(
        lambda a: jnp.concatenate(
            [a, jnp.repeat(a[..., :1], pad, axis=-1)], axis=-1
        ),
        tree,
    )


def make_verify_sharded(mesh: Mesh, axis: str = "batch"):
    """Build a jitted, mesh-sharded verify kernel.

    Returns fn(pk_aff, sig_aff, h_aff, wbits) -> bool where all inputs
    carry the global batch on the trailing axis.  Batches not divisible
    by the mesh size are padded up with duplicates of set 0 (AND-safe:
    padding cannot flip the conjunction) — each distinct padded extent
    traces its own program, same as any new batch size.
    """
    in_spec = batch_spec(2, axis=axis)  # (limbs, B) arrays split on B

    def local_part(pk_aff, sig_aff, h_aff, wbits):
        # --- per-device heavy compute on the local shard ---
        ok_sub = jnp.all(P.g2_subgroup_check(sig_aff))
        wpk = P.scalar_mul_bits(P.FP_OPS, P.from_affine(P.FP_OPS, pk_aff), wbits)
        wsig = P.scalar_mul_bits(
            P.FP2_OPS, P.from_affine(P.FP2_OPS, sig_aff), wbits
        )
        S_local = _tree_reduce_g2(wsig)  # batch-1 G2 jacobian
        wpk_aff = P.to_affine(P.FP_OPS, wpk, F.fp_inv)
        f_local = PR.miller_loop(wpk_aff, h_aff)
        g_local = PR.gt_product(f_local)  # batch-1 fp12
        # --- tiny cross-device combine over ICI (parallel/mesh.py) ---
        g_all = allgather_tree(g_local, axis)
        S_all = allgather_tree(S_local, axis)
        ok_all = and_reduce(ok_sub, axis)
        # --- replicated epilogue: fold in (-G1, S) and final-exponentiate ---
        g = PR.gt_product(g_all)
        S = _tree_reduce_g2(S_all)
        s_inf = P.pt_is_infinity(P.FP2_OPS, S)
        S_aff = P.to_affine(P.FP2_OPS, S, T.fp2_inv)
        neg_gen = _neg_gen_const()
        f_last = PR.miller_loop(neg_gen, S_aff)
        one = PR._fp12_one_like_from_fp2(S_aff[0])
        f_last = T.fp12_select(jnp.broadcast_to(s_inf, (1,)), one, f_last)
        total = T.fp12_mul(g, f_last)
        ok_pair = PR.final_exp_is_one(total)
        return jnp.reshape(ok_pair & ok_all, ())

    # check_vma=False — re-verified against this jax version (r5): with
    # check_vma=True the first field-core scan fails typing with
    #   "input carry acc has type uint32[52,18] but the corresponding
    #    output carry component has type uint32[52,18]{V:batch} ...
    #    might be fixed by applying jax.lax.pcast(..., ('batch',),
    #    to='varying') to the initial carry value"
    # because every Horner/Montgomery scan in fp.py initializes its carry
    # from a replicated zero/constant while the loop body mixes in
    # batch-varying limbs.  Fixing it "properly" means pcast at every
    # carry init — but those inits live in the limb library, which is
    # used both inside and outside shard_map, and pcast with an axis
    # name is an error outside a mesh context.  Threading an
    # inside-a-mesh flag through fp.py buys type checking and costs a
    # second code path in the hottest code; correctness is instead
    # pinned by the shard-vs-single bit-equality tests
    # (test_multichip.py), the poisoned-batch rejection in the
    # driver's dryrun, and — statically — the spmd audit family
    # (analysis/spmd_lint.py), whose own replication check proves the
    # scan-with-replicated-carry pattern device-identical through
    # exactly the typing gap check_vma trips over here.
    sharded = _shard_map(
        local_part,
        mesh=mesh,
        in_specs=(in_spec, in_spec, in_spec, in_spec),
        out_specs=PS(),
    )
    jitted = jax.jit(sharded)
    n = int(mesh.devices.size)

    def verify(pk_aff, sig_aff, h_aff, wbits):
        pad = (-_trailing_extent(pk_aff)) % n
        if pad:
            pk_aff, sig_aff, h_aff, wbits = _pad_tail_cols(
                (pk_aff, sig_aff, h_aff, wbits), pad
            )
        return jitted(pk_aff, sig_aff, h_aff, wbits)

    return verify


def make_pair_sharded_aggregate_verify(mesh: Mesh, axis: str = "batch"):
    """Shard the PAIRS of one large accumulation across the mesh — the
    SURVEY §2.8/§5 "sequence scaling" axis.  One aggregate-verify
    (blst.rs:244-255: distinct messages, ONE signature) whose (pk_i, H_i)
    pairs spread over devices; each device Miller-loops its pair shard and
    multiplies its local GT partial, the partials combine with an fp12
    RING-reduction over ICI (the exact ring-attention accumulation shape —
    the GT product is associative), and the single final exponentiation
    runs replicated.

    Returns fn(pk_aff, h_aff, sig_aff) -> bool: pk/h carry the global pair
    count on the trailing axis; sig is the batch-1 aggregate signature,
    replicated.  Pair counts not divisible by the mesh size are padded up
    with duplicates of pair 0 plus a sharded pad mask — unlike the
    AND-reduce kernel, a padded pair's Miller factor would multiply into
    the single GT product and change the verdict, so padded lanes are
    selected to fp12 one before the local reduce."""
    pair_spec = batch_spec(2, axis=axis)

    def local_part(pk_aff, h_aff, sig_aff, pad_mask):
        ok_sub = jnp.all(P.g2_subgroup_check(sig_aff))
        f_local = PR.miller_loop(pk_aff, h_aff)
        one = PR._fp12_one_like_from_fp2(f_local[0][0])
        f_local = T.fp12_select(pad_mask, one, f_local)
        g_local = PR.gt_product(f_local)  # one fp12 partial per device
        # --- the ring: N-1 ppermute hops, each folding the neighbour's
        # partial into the accumulator (ICI traffic = one fp12 per hop) ---
        g = ring_reduce(g_local, T.fp12_mul, axis)
        # --- replicated epilogue: fold e(-G1, sig), final exp ----------
        neg_gen = _neg_gen_const()
        f_last = PR.miller_loop(neg_gen, sig_aff)
        total = T.fp12_mul(PR.gt_product(g), f_last)
        ok_pair = PR.final_exp_is_one(total)
        return jnp.reshape(ok_pair & ok_sub, ())

    sharded = _shard_map(
        local_part,
        mesh=mesh,
        in_specs=(pair_spec, pair_spec, PS(), batch_spec(1, axis=axis)),
        out_specs=PS(),
    )
    jitted = jax.jit(sharded)
    n = int(mesh.devices.size)

    def aggregate_verify(pk_aff, h_aff, sig_aff):
        pairs = _trailing_extent(pk_aff)
        pad = (-pairs) % n
        if pad:
            pk_aff, h_aff = _pad_tail_cols((pk_aff, h_aff), pad)
        pad_mask = jnp.arange(pairs + pad) >= pairs
        return jitted(pk_aff, h_aff, sig_aff, pad_mask)

    return aggregate_verify
