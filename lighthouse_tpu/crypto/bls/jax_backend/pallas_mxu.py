"""MXU-mapped Montgomery multiply: 13-bit re-limbed dot-product kernel.

Every kernel before this one ran limb arithmetic on the VPU while the
MXU — the chip's dominant FLOPs engine — sat idle (ROADMAP item 1).
This module remaps the schoolbook column accumulation onto the MXU as a
small matmul:

    column_t = S @ outer(a, b).reshape(n*n, T)

where ``S`` is a static 0/1 *banded reduction matrix* (row k has ones at
every flattened (i, j) with i + j == k) shared by all lanes, and the
batch T rides the matmul's N dimension.  That sidesteps the objection
that killed the earlier int8 sketch (both matmul operands varying per
lane): the per-lane data enters as the (n*n, T) right-hand side, the
weights are the lane-invariant band structure.

Why 13 bits: RANGE_REPORT.json proves the native 26x15 representation
peaks at ~2^34.7 per column — over the int32 2^31 MXU accumulator — so
operands are re-limbed to the 13-bit split (limbs.SPEC13).  26*15 =
390 = 30*13, so both splits share R = 2^390: the Montgomery constants
are the same integers and the 15<->13 conversion is pure limb
regrouping (exact, in-kernel, a handful of shifts/masks per limb).
Column ceiling: 31 * 8193 * 8193 < 2^30.96 < 2^31, machine-checked by
analysis/range_lint's dot_general transfer handler.

Contract (mirrors pallas_fp.mont_mul_limbs): (26, T) quasi-normalized
15-bit uint32 limbs in, bound-product <= 2000 in units of P, STRICT
15-bit limbs out, value = a*b*R^-1 + kP within the same
MONT_DIVISOR/MONT_EPS envelope fp.mont_mul labels.  Note the *bytes*
may differ from the VPU kernel on a ~2^-13 sliver of inputs: both
truncate m = t*P' mod R to a quasi-normalized representative, and the
two planes can disagree by exactly R there (output shifted by one P,
still in-envelope).  The differential corpus in tests/test_pallas_fp.py
pins byte-identity on random + all-QMAX inputs.

Routing: fp.mont_mul, the megachains, and the fused Miller loop all
route through fp.mxu_enabled().  This plane is the ``mxu13`` arm of the
kernel-arm registry (autotune.ARM_TABLE): on a tuned boot the installed
per-device-kind plan decides per batch shape whether programs trace
through it (fp.mxu_for_batch), with LIGHTHOUSE_TPU_MXU=1 / fp.set_mxu
demoted to explicit overrides that force it everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L
from . import pallas_fp as PF

BITS13 = 13
NL13 = L.SPEC13.n  # 31: 30 limbs span R=2^390 + 1 spill limb (quasi-15 in)
NCOLS = 2 * NL13 - 1  # 61 schoolbook columns
M13 = np.uint32((1 << BITS13) - 1)
MASK15 = np.uint32((1 << 15) - 1)

# Montgomery constants on the 13-bit plane — the SAME integers as the
# 15-bit plane's (shared R = 2^390), re-limbed.  P and P' span 30 limbs
# (< 2^390); the 31st row is the plane's spill limb, identically zero.
# Host-side reference only: the kernels receive the 15-bit constants as
# operands (pallas_call forbids captured array constants) and re-limb
# them in-kernel with _to13 — exact in value, quasi-13 limbs.
_P13 = L.SPEC13.p_limbs.reshape(NL13, 1)
_PP13 = L.SPEC13.pprime_limbs.reshape(NL13, 1)


def _band_matrix():
    """(61, 961) 0/1 reduction matrix: S[k, 31*i + j] = [i + j == k].

    S @ outer(a, b).reshape(961, T) computes every schoolbook column sum
    in one matmul; S is lane-invariant, so it sits in the MXU weights
    while T rides the N dimension.  Built from iota inside the traced
    kernel (pallas_call forbids captured array constants; the compare
    folds to a constant band at compile time)."""
    k = jax.lax.broadcasted_iota(jnp.int32, (NCOLS, NL13 * NL13), 0)
    flat = jax.lax.broadcasted_iota(jnp.int32, (NCOLS, NL13 * NL13), 1)
    return ((flat // NL13 + flat % NL13) == k).astype(jnp.int32)


def _compress13(cols):
    """One 13-bit carry pass (the pad+slice idiom of PF._compress1 —
    Mosaic has no scatter-add).  The top row's carry-out is statically
    zero for every use here: raw column 60 is a[30]*b[30] <= ~2^4 and
    stays < 2^13 through all passes (range_lint-verified)."""
    lo = cols & M13
    hi = cols >> BITS13
    return lo + jnp.pad(hi[:-1], ((1, 0), (0, 0)))


def _to13(a15):
    """(26, T) quasi-15 limbs -> (31, T) quasi-13 limbs (<= 8193), exact
    in value.  Quasi limbs are NOT bit fields, so this is not a regroup:
    each 15-bit limb lands at bit position 15*i = 13*q + r and is split
    into three 13-bit chunks accumulated at columns q, q+1, q+2 (the
    third only when r >= 11 can make it nonzero).  Column sums stay
    <= 2 full chunks + 1 spill < 2^14; one carry pass quasi-normalizes."""
    cols = [[] for _ in range(NL13)]
    for i in range(26):
        q, r = divmod(15 * i, BITS13)
        v = a15[i] << r  # <= QMAX << 12 < 2^27.1
        cols[q].append(v & M13)
        cols[q + 1].append((v >> BITS13) & M13)
        if (int(L.SPEC15.qmax) << r) >> 26:
            cols[q + 2].append(v >> 26)
    stacked = jnp.stack(
        [functools.reduce(lambda x, y: x + y, c) for c in cols], axis=0
    )
    return _compress13(stacked)


def _to15(a13):
    """(31, T) STRICT 13-bit limbs of a value < 2^390 -> (26, T) strict
    15-bit limbs, exact.  Strict limbs ARE bit fields, so this is a pure
    regroup: out[q] collects bits [15q, 15q+15) from the two (or, when
    15q falls 12 bits into a 13-bit limb, three) straddling source
    limbs — disjoint bit ranges, so plain adds then one 15-bit mask."""
    rows = []
    for q in range(26):
        pos = 15 * q
        j, r = divmod(pos, BITS13)
        acc = a13[j] >> r
        acc = acc + (a13[j + 1] << (BITS13 - r))
        if 2 * BITS13 - r < 15:  # r == 12: a third limb straddles the window
            acc = acc + (a13[j + 2] << (2 * BITS13 - r))
        rows.append(acc & MASK15)
    return jnp.stack(rows, axis=0)


def _dot_cols(a13, b13):
    """All 61 schoolbook columns of a 31x31 limb product as ONE matmul.

    The (31, 31, T) outer product (uint32, products <= 8193^2 < 2^27)
    flattens to the (961, T) right-hand side; the static band matrix
    contracts it on the MXU with int32 accumulation
    (preferred_element_type) — column sums <= 31 * 8193^2 < 2^31, the
    budget the whole re-limbing exists to meet.  Three 13-bit carry
    passes bring the columns back to quasi-13 (<= 8192)."""
    T = a13.shape[1]
    outer = (a13[:, None, :] * b13[None, :, :]).reshape(NL13 * NL13, T)
    s_band = _band_matrix()
    t = jax.lax.dot_general(
        s_band,
        outer.astype(jnp.int32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    cols = t.astype(jnp.uint32)
    return _compress13(_compress13(_compress13(cols)))


def _pad_row(x):
    """Append the zero spill row taking a (30, T) slice back to the
    31-row plane (full-width pad, not scatter)."""
    return jnp.pad(x, ((0, NL13 - x.shape[0]), (0, 0)))


def mont_core_mxu(a15, b15, pl15, pp15):
    """One full Montgomery product on in-kernel (26, T) quasi-15 values
    -> strict 15-bit limbs.  Same operand signature as PF._mont_core
    (the 15-bit P / P' constant tiles ride in as refs and are re-limbed
    in-kernel — exact in value, so the Montgomery algebra is untouched),
    same algebra, on the 13-bit plane with MXU column sums:

      t = a*b                 (61 quasi-13 columns)
      m = (t * P') mod R      (columns 0..29 of the dot — truncation at
                               the 30-limb radix boundary drops exact
                               multiples of 2^390)
      u = m * P
      s = t + u; out = s / R  (61-step carry chain; low 30 columns
                               vanish, columns 30..60 are the result)
    """
    a13 = _to13(a15)
    b13 = _to13(b15)
    pp13 = _to13(pp15)
    p13 = _to13(pl15)
    t = _dot_cols(a13, b13)  # (61, T) <= 8192
    m = _dot_cols(_pad_row(t[:30]), pp13)[:30]
    u = _dot_cols(_pad_row(m), p13)
    s = t + u  # <= 2 * 8192 = 2^14 per column
    carry = jnp.zeros((s.shape[1],), dtype=jnp.uint32)
    out_rows = []
    for k in range(NCOLS):
        tcol = s[k] + carry
        carry = tcol >> BITS13
        if k >= 30:
            out_rows.append(tcol & M13)
    out13 = jnp.stack(out_rows, axis=0)  # (31, T) strict, value < 2^390
    return _to15(out13)


def mont_mul_limbs(a_limbs, b_limbs, interpret: bool = False):
    """(26, N) x (26, N) quasi limbs -> (26, N) strict Montgomery
    product via the MXU dot kernel — the explicit-route entry for tests
    and bench A/Bs.  Delegates to pallas_fp.mont_mul_limbs(mxu=True):
    there is ONE kernel-call family keyed on (shape, interpret, mxu),
    so padding/tiling stay identical to the VPU path by construction."""
    return PF.mont_mul_limbs(a_limbs, b_limbs, interpret=interpret,
                             mxu=True)
