"""Pallas TPU kernel for the Montgomery limb multiply — the hot op.

PERF.md plan item 1: the XLA `mont_mul` lowers to ~3 Horner `lax.scan`s
whose every step materializes a shifted copy of the (52, B) accumulator
(concatenate + two scatter-adds) — the measured kernel is dispatch/copy
bound, not multiply bound.  This kernel runs the whole Montgomery
product — wide schoolbook, P' low product, P wide product, 52-limb carry
normalization — as ONE Pallas program per lane tile with every
intermediate in VMEM, loops unrolled at trace time (static 26/52-step
Python loops), and the shift structure expressed as static-slice
accumulations the Mosaic compiler keeps on-chip.

Same representation contract as fp.mont_mul (fp.py): 26 x 15-bit
quasi-normalized uint32 limbs, Montgomery radix 2^390, inputs with
bound-product <= 2000 in units of P, STRICT limbs out.  The wrapper is a
drop-in for the three-scan body; bound bookkeeping stays in fp.LFp.

Enable with LIGHTHOUSE_TPU_PALLAS=1 (fp.mont_mul routes here on TPU
backends; the lax.scan path remains the CPU/test reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import fp as F

LANE_TILE = 512  # lanes per grid step (multiple of 128)
CHAIN_WINDOW = 4  # chain window width: 2^w precomputed powers, w sqr + 1 mul


def pick_tile(n: int) -> int:
    """The ONE tiling rule for lane-padded pallas calls: full LANE_TILE
    when the batch fills it, else the smallest 128-multiple cover —
    every fused kernel family must pad identically or their operands
    misalign."""
    return LANE_TILE if n >= LANE_TILE else max(128, -(-n // 128) * 128)

_P_COLS = np.asarray(F.int_to_limbs(F.P_INT)).reshape(26, 1)
_PP_COLS = np.asarray(F.int_to_limbs(F.PPRIME_INT)).reshape(26, 1)

MASK = np.uint32((1 << 15) - 1)


def _compress1(cols):
    """One carry pass (fp.compress1, in-kernel): lo < 2^15 plus the
    column below's high bits.  On the wide-product accumulator
    (<= 1,677,799 < 2^20.7) one pass lands <= 32818 and a second
    <= 32768 <= QMAX (intervals range_lint-verified).  Shift expressed
    as pad+slice (Mosaic has no scatter-add)."""
    lo = cols & MASK
    hi = cols >> 15
    shifted = jnp.pad(hi[:-1], ((1, 0), (0, 0)))
    return lo + shifted


def _acc_add(acc, rows, offset: int):
    """acc += rows placed at row ``offset`` — expressed as a zero-pad to
    the accumulator height plus a full-width add (Mosaic lowers
    pad/concatenate + add; it has neither scatter-add nor value-level
    dynamic_slice)."""
    tail = acc.shape[0] - offset - rows.shape[0]
    return acc + jnp.pad(rows, ((offset, tail), (0, 0)))


def _wide_product(a, b):
    """Schoolbook sum_i a_i * b * 2^(15 i); a, b (26, T) quasi limbs.
    Returns (52, T) columns, two carry passes applied (<= 32768 <= QMAX,
    range_lint-verified).  All accumulator updates are full-width
    in-bounds slice-adds — the clipped-slice variant lowers to a
    scatter Pallas cannot stage."""
    T = a.shape[1]
    acc = jnp.zeros((52, T), dtype=jnp.uint32)
    for i in range(26):
        p = a[i][None, :] * b  # (26, T) 32-bit products
        plo = p & MASK
        phi = p >> 15
        acc = _acc_add(acc, plo, i)
        acc = _acc_add(acc, phi, i + 1)
        # column sums peak at 1,677,799 < 2^20.7 (range_lint): no overflow
    return _compress1(_compress1(acc))


def _wide_square(a):
    """Schoolbook square via the j >= i triangle: cross products doubled,
    diagonal single — 351 limb products instead of 676.  Shapes stay
    static per unrolled i (tail slices), which Mosaic handles."""
    T = a.shape[1]
    acc = jnp.zeros((52, T), dtype=jnp.uint32)
    for i in range(26):
        tail = a[i:]  # (26-i, T)
        p = a[i][None, :] * tail  # a_i * a_j, j >= i
        # double the cross terms (j > i); diagonal stays single.
        # products <= QMAX^2 ~ 2^30.01, doubled < 2^31.02 — the
        # repo-wide uint32 high-water mark (range_lint max_acc): the
        # int32 MXU budget is the binding one for any matmul remap.
        # i=25 has no cross terms: p[1:] would be a zero-row vector,
        # which real Mosaic lowering rejects ("vector types must have
        # positive constant sizes") even though interpret mode allows it
        if p.shape[0] > 1:
            p = jnp.concatenate([p[:1], p[1:] + p[1:]], axis=0)
        plo = p & MASK
        phi = p >> 15
        acc = _acc_add(acc, plo, 2 * i)
        acc = _acc_add(acc, phi, 2 * i + 1)
    return _compress1(_compress1(acc))


def _mont_reduce(t, pl_, pp):
    """Montgomery reduction of a (52, T) wide product: m = (t·P') mod R
    (the low half of the full product — columns < 26 coincide with the
    low product's), u = m·P, then one full carry normalization whose low
    26 limbs vanish (divisible by R)."""
    m = _wide_product(t[:26], pp)[:26]
    u = _wide_product(m, pl_)
    s = t + u  # <= 2^16 per column: both double-compressed <= 32768 (range_lint)
    carry = jnp.zeros((t.shape[1],), dtype=jnp.uint32)
    out_rows = []
    for k in range(52):
        tcol = s[k] + carry
        carry = tcol >> 15
        if k >= 26:
            out_rows.append(tcol & MASK)
    return jnp.stack(out_rows, axis=0)


def _mont_sqr_core(a, pl_, pp):
    """Montgomery square: triangle wide product, shared reduction tail."""
    return _mont_reduce(_wide_square(a), pl_, pp)


def _mont_core(a, b, pl_, pp):
    """One full Montgomery product on in-kernel values -> strict limbs."""
    return _mont_reduce(_wide_product(a, b), pl_, pp)


def _mont_kernel(a_ref, b_ref, p_ref, pp_ref, o_ref):
    o_ref[:] = _mont_core(a_ref[:], b_ref[:], p_ref[:], pp_ref[:])


def _mont_kernel_mxu(a_ref, b_ref, p_ref, pp_ref, o_ref):
    """Same operand contract as _mont_kernel, column sums on the MXU
    (13-bit re-limbed dot-product core, pallas_mxu.py)."""
    from . import pallas_mxu

    o_ref[:] = pallas_mxu.mont_core_mxu(
        a_ref[:], b_ref[:], p_ref[:], pp_ref[:]
    )


def _core_pair(mxu: bool):
    """(mul, sqr) cores for a kernel family: the VPU schoolbook pair or
    the MXU dot-product core (which has no triangle trick — sqr is
    mul(a, a); the dot path's win is the matmul, not the product
    count).  Every fused kernel family threads ``mxu`` through its
    lru_cache factory key so both programs can coexist in one
    process."""
    if mxu:
        from . import pallas_mxu

        mont = pallas_mxu.mont_core_mxu
        return mont, lambda a, pl_, pp: mont(a, a, pl_, pp)
    return _mont_core, _mont_sqr_core


def _select_power(d, powers):
    """Value-level one-hot select of powers[d] for a traced digit d —
    Mosaic has no dynamic gather over a trace-time list, so this is
    2^w - 1 vector selects (cheap next to the w Montgomery squares each
    digit already costs)."""
    sel = powers[0]
    for k in range(1, len(powers)):
        sel = jnp.where(d == k, powers[k], sel)
    return sel


def _make_megachain_kernel(w: int, n_digits: int, mxu: bool = False):
    """The WHOLE exponent chain as ONE Pallas program: the MSB-first
    base-2^w digit tape rides in as a scalar-prefetch operand (SMEM),
    the 2^w-entry power table is built in-kernel (2^w - 2 Montgomery
    products), and a fori_loop walks the tape — w squares plus one
    table-selected multiply per digit.  The compiled program depends
    only on (w, n_digits), never on the digit VALUES: the Fermat
    affinization chain, the h2c sqrt chains, and any future exponent of
    equal digit count share one Mosaic program.  The previous design
    stacked one pallas_call per digit (~96 dispatches for Fermat alone)
    and keyed programs per window pattern (~24 distinct programs), which
    is what made the chains+miller composition a pathological >6,700 s
    Mosaic compile — session2 06:52Z.

    Digit 0 multiplies by the Montgomery one (value-preserving), so the
    loop body is uniform and needs no predication."""

    mont, sqr = _core_pair(mxu)

    def megachain_kernel(tape_ref, base_ref, p_ref, pp_ref, one_ref,
                         o_ref):
        base = base_ref[:]
        pl_, pp = p_ref[:], pp_ref[:]
        powers = [one_ref[:], base]
        for _ in range(2, 1 << w):
            powers.append(mont(powers[-1], base, pl_, pp))

        def step(i, acc):
            for _ in range(w):
                acc = sqr(acc, pl_, pp)  # triangle sqr (~-16%) on VPU
            sel = _select_power(tape_ref[i], powers)
            return mont(acc, sel, pl_, pp)

        acc = _select_power(tape_ref[0], powers)
        o_ref[:] = jax.lax.fori_loop(1, n_digits, step, acc)

    return megachain_kernel


@functools.lru_cache(maxsize=64)
def _mont_call(n_padded: int, tile: int, interpret: bool,
               mxu: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n_padded // tile,)
    spec = pl.BlockSpec((26, tile), lambda i: (0, i),
                        memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((26, tile), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _mont_kernel_mxu if mxu else _mont_kernel,
        out_shape=jax.ShapeDtypeStruct((26, n_padded), jnp.uint32),
        grid=grid,
        in_specs=[spec, spec, const_spec, const_spec],
        out_specs=spec,
        interpret=interpret,
    )


_BIAS2_COLS = np.asarray(F._biased_kp(2)).astype(np.uint32).reshape(26, 1)
_BIAS16_COLS = np.asarray(F._biased_kp(16)).astype(np.uint32).reshape(26, 1)


def _sub_biased(a, b, bias):
    """Value a - b + k·P, limb-safe when every bias limb >= b's quasi
    limbs (fp._biased_kp boosts all non-top limbs past QMAX) and the
    bias's borrowed-from top limb dominates b's top limb
    (fp._sub_top_dominates — ``k >= b's value bound`` alone is NOT
    sufficient; the in-kernel uses here are interval-proven by
    range_lint over the fp2/Miller programs)."""
    return _compress1((a + bias) - b)


def _fp2_sqr_core(a0, a1, pl_, pp, b16, mont=_mont_core):
    """(a0 + a1·u)²: real (a0+a1)(a0-a1), imag 2·a0·a1 (u² = -1).
    Worst-case input is post-mul (a0 <= ~3.2P, a1 <= ~5.2P): the k=16
    bias covers the subtrahend; outputs re-normalize to (<=1.4P, <=2.4P)."""
    s = _compress1(a0 + a1)
    d = _sub_biased(a0, a1, b16)
    r0 = mont(s, d, pl_, pp)
    t = mont(a0, a1, pl_, pp)
    return r0, _compress1(t + t)


def _fp2_mul_core(a0, a1, b0, b1, pl_, pp, b2, mont=_mont_core):
    """Karatsuba: v0 - v1 + (cross - v0 - v1)·u.  The v's are Montgomery
    outputs (< 1.2P), so k=2 biases suffice; outputs stay <= (3.2P, 5.2P)
    — inside the square's envelope above."""
    v0 = mont(a0, b0, pl_, pp)
    v1 = mont(a1, b1, pl_, pp)
    m = mont(_compress1(a0 + a1), _compress1(b0 + b1), pl_, pp)
    r0 = _sub_biased(v0, v1, b2)
    r1 = _sub_biased(_sub_biased(m, v0, b2), v1, b2)
    return r0, r1


def _make_fp2_megachain_kernel(w: int, n_digits: int, mxu: bool = False):
    """Fp2 whole-chain program, same digit-tape design as
    _make_megachain_kernel (the power table is built in-kernel with
    2^w - 2 Karatsuba multiplies; powers[0] is the Montgomery one so a
    0 digit is value-preserving).

    Bounds: table entries and the loop accumulator are worst-case
    post-mul (<=3.2P, <=5.2P), which _fp2_sqr_core's envelope admits;
    every multiply's subtrahends are Montgomery outputs (<1.2P) so the
    k=2 biases hold for any in-envelope operand — the envelope closes
    across fori_loop iterations exactly as it did across the old
    stacked per-digit calls."""

    mont, _ = _core_pair(mxu)

    def fp2_megachain_kernel(tape_ref, a0_ref, a1_ref, p_ref, pp_ref,
                             b16_ref, b2_ref, one_ref, o0_ref, o1_ref):
        a0, a1 = a0_ref[:], a1_ref[:]
        pl_, pp = p_ref[:], pp_ref[:]
        b16, b2 = b16_ref[:], b2_ref[:]
        powers = [(one_ref[:], jnp.zeros_like(a0)), (a0, a1)]
        for _ in range(2, 1 << w):
            p0, p1 = powers[-1]
            powers.append(
                _fp2_mul_core(p0, p1, a0, a1, pl_, pp, b2, mont=mont)
            )
        pow0 = [p[0] for p in powers]
        pow1 = [p[1] for p in powers]

        def step(i, carry):
            c0, c1 = carry
            for _ in range(w):
                c0, c1 = _fp2_sqr_core(c0, c1, pl_, pp, b16, mont=mont)
            d = tape_ref[i]
            return _fp2_mul_core(c0, c1, _select_power(d, pow0),
                                 _select_power(d, pow1), pl_, pp, b2,
                                 mont=mont)

        d0 = tape_ref[0]
        acc = (_select_power(d0, pow0), _select_power(d0, pow1))
        o0_ref[:], o1_ref[:] = jax.lax.fori_loop(1, n_digits, step, acc)

    return fp2_megachain_kernel


@functools.lru_cache(maxsize=32)
def _fp2_megachain_call(n_padded: int, tile: int, w: int, n_digits: int,
                        interpret: bool, mxu: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    spec = pl.BlockSpec((26, tile), lambda i, tape: (0, i),
                        memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((26, tile), lambda i, tape: (0, 0),
                              memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((26, n_padded), jnp.uint32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_padded // tile,),
        in_specs=[spec, spec, const_spec, const_spec, const_spec,
                  const_spec, const_spec],
        out_specs=(spec, spec),
    )
    return pl.pallas_call(
        _make_fp2_megachain_kernel(w, n_digits, mxu),
        out_shape=(out_shape, out_shape),
        grid_spec=grid_spec,
        interpret=interpret,
    )


def fp2_pow_chain(a0_limbs, a1_limbs, bits: tuple[int, ...],
                  w: int = CHAIN_WINDOW, interpret: bool | None = None,
                  mxu: bool | None = None):
    """(a0 + a1·u)^e for static MSB-first bits (leading bit must be 1);
    inputs reduced (bound <= 2).  ONE pallas dispatch: the digit tape is
    a scalar-prefetch operand, power table and window walk live in the
    kernel.  Returns raw limb pair (exit bounds <= (3.2P, 5.2P); callers
    re-reduce).  interpret=None resolves by backend (interpret off-TPU),
    so forced device paths still execute on CPU."""
    assert bits and bits[0] == 1
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if mxu is None:
        mxu = F.mxu_enabled()
    n = a0_limbs.shape[-1]
    tile = pick_tile(n)
    n_padded = -(-n // tile) * tile
    if n_padded != n:
        pad = ((0, 0), (0, n_padded - n))
        a0_limbs = jnp.pad(a0_limbs, pad)
        a1_limbs = jnp.pad(a1_limbs, pad)
    consts = [
        jnp.broadcast_to(jnp.asarray(c, dtype=jnp.uint32), (26, tile))
        for c in (_P_COLS, _PP_COLS, _BIAS16_COLS, _BIAS2_COLS)
    ]
    one0 = jnp.broadcast_to(
        jnp.asarray(np.asarray(F.int_to_limbs(F.R1_INT)).reshape(26, 1),
                    dtype=jnp.uint32), (26, tile))
    digits = _window_digits(
        "".join("1" if b else "0" for b in bits), w)
    tape = jnp.asarray(digits, dtype=jnp.int32)
    call = _fp2_megachain_call(n_padded, tile, w, len(digits), interpret,
                               mxu)
    acc0, acc1 = call(tape, a0_limbs, a1_limbs, *consts, one0)
    if n_padded != n:
        return acc0[:, :n], acc1[:, :n]
    return acc0, acc1


@functools.lru_cache(maxsize=64)
def _megachain_call(n_padded: int, tile: int, w: int, n_digits: int,
                    interpret: bool, mxu: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    spec = pl.BlockSpec((26, tile), lambda i, tape: (0, i),
                        memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((26, tile), lambda i, tape: (0, 0),
                              memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_padded // tile,),
        in_specs=[spec, const_spec, const_spec, const_spec],
        out_specs=spec,
    )
    return pl.pallas_call(
        _make_megachain_kernel(w, n_digits, mxu),
        out_shape=jax.ShapeDtypeStruct((26, n_padded), jnp.uint32),
        grid_spec=grid_spec,
        interpret=interpret,
    )


def _window_digits(bitstr: str, w: int) -> list[int]:
    """MSB-aligned base-2^w digits of a binary string (shared by both
    chain families — the decomposition must never drift between them)."""
    pad = (-len(bitstr)) % w
    bitstr = "0" * pad + bitstr
    return [int(bitstr[i:i + w], 2) for i in range(0, len(bitstr), w)]


def pow_chain_limbs(base_limbs, exponent: int,
                    interpret: bool | None = None, w: int = CHAIN_WINDOW,
                    mxu: bool | None = None):
    """base^exponent (Montgomery domain) as ONE pallas dispatch: the
    MSB-first base-2^w digit tape is a scalar-prefetch operand, the
    power table is built in-kernel, and a fori_loop runs w squares + one
    table-selected multiply per digit (digit 0 multiplies by the
    Montgomery one — value-preserving, keeps the loop body uniform).
    For the 381-bit Fermat exponent this is ~475 in-kernel products in
    one program/dispatch, vs ~96 stacked dispatches over ~24 distinct
    programs for the old per-window design.

    base must be strict/quasi limbs of a value bounded < 4.3P (mont
    outputs and reduced values qualify: every in-kernel product is then
    strict×strict, far under the bound-product ceiling).  interpret=None
    resolves by backend (interpret off-TPU), so forced device paths
    still execute on CPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if mxu is None:
        mxu = F.mxu_enabled()
    digits = _window_digits(bin(exponent)[2:], w)
    tape = jnp.asarray(digits, dtype=jnp.int32)

    n = base_limbs.shape[-1]
    tile = pick_tile(n)
    n_padded = -(-n // tile) * tile
    if n_padded != n:
        base_limbs = jnp.pad(base_limbs, ((0, 0), (0, n_padded - n)))
    p_tile = jnp.broadcast_to(
        jnp.asarray(_P_COLS, dtype=jnp.uint32), (26, tile)
    )
    pp_tile = jnp.broadcast_to(
        jnp.asarray(_PP_COLS, dtype=jnp.uint32), (26, tile)
    )
    one = jnp.broadcast_to(
        jnp.asarray(
            np.asarray(F.int_to_limbs(F.R1_INT)).reshape(26, 1),
            dtype=jnp.uint32),
        (26, tile))
    call = _megachain_call(n_padded, tile, w, len(digits), interpret, mxu)
    acc = call(tape, base_limbs, p_tile, pp_tile, one)
    return acc[:, :n] if n_padded != n else acc


def mont_mul_limbs(a_limbs, b_limbs, interpret: bool = False,
                   mxu: bool | None = None):
    """(26, N) x (26, N) quasi limbs -> (26, N) strict Montgomery product.
    Pads N up to a lane multiple; slices back.  mxu=None resolves from
    the LIGHTHOUSE_TPU_MXU gate (fp.mxu_enabled); True routes the column
    accumulation through the 13-bit dot-product core (pallas_mxu.py)."""
    if mxu is None:
        mxu = F.mxu_enabled()
    n = a_limbs.shape[-1]
    tile = pick_tile(n)
    n_padded = -(-n // tile) * tile
    if n_padded != n:
        pad = ((0, 0), (0, n_padded - n))
        a_limbs = jnp.pad(a_limbs, pad)
        b_limbs = jnp.pad(b_limbs, pad)
    p_tile = jnp.broadcast_to(
        jnp.asarray(_P_COLS, dtype=jnp.uint32), (26, tile)
    )
    pp_tile = jnp.broadcast_to(
        jnp.asarray(_PP_COLS, dtype=jnp.uint32), (26, tile)
    )
    out = _mont_call(n_padded, tile, interpret, mxu)(
        a_limbs, b_limbs, p_tile, pp_tile
    )
    return out[:, :n] if n_padded != n else out
