"""Pallas TPU kernel for the Montgomery limb multiply — the hot op.

PERF.md plan item 1: the XLA `mont_mul` lowers to ~3 Horner `lax.scan`s
whose every step materializes a shifted copy of the (52, B) accumulator
(concatenate + two scatter-adds) — the measured kernel is dispatch/copy
bound, not multiply bound.  This kernel runs the whole Montgomery
product — wide schoolbook, P' low product, P wide product, 52-limb carry
normalization — as ONE Pallas program per lane tile with every
intermediate in VMEM, loops unrolled at trace time (static 26/52-step
Python loops), and the shift structure expressed as static-slice
accumulations the Mosaic compiler keeps on-chip.

Same representation contract as fp.mont_mul (fp.py): 26 x 15-bit
quasi-normalized uint32 limbs, Montgomery radix 2^390, inputs with
bound-product <= 2000 in units of P, STRICT limbs out.  The wrapper is a
drop-in for the three-scan body; bound bookkeeping stays in fp.LFp.

Enable with LIGHTHOUSE_TPU_PALLAS=1 (fp.mont_mul routes here on TPU
backends; the lax.scan path remains the CPU/test reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import fp as F

LANE_TILE = 512  # lanes per grid step (multiple of 128)

_P_COLS = np.asarray(F.int_to_limbs(F.P_INT)).reshape(26, 1)
_PP_COLS = np.asarray(F.int_to_limbs(F.PPRIME_INT)).reshape(26, 1)

MASK = np.uint32((1 << 15) - 1)


def _compress1(cols):
    """One carry pass (fp.compress1, in-kernel): quasi-normalize < 2^16.2.
    Shift expressed as pad+slice (Mosaic has no scatter-add)."""
    lo = cols & MASK
    hi = cols >> 15
    shifted = jnp.pad(hi[:-1], ((1, 0), (0, 0)))
    return lo + shifted


def _acc_add(acc, rows, offset: int):
    """acc += rows placed at row ``offset`` — expressed as a zero-pad to
    the accumulator height plus a full-width add (Mosaic lowers
    pad/concatenate + add; it has neither scatter-add nor value-level
    dynamic_slice)."""
    tail = acc.shape[0] - offset - rows.shape[0]
    return acc + jnp.pad(rows, ((offset, tail), (0, 0)))


def _wide_product(a, b):
    """Schoolbook sum_i a_i * b * 2^(15 i); a, b (26, T) quasi limbs.
    Returns (52, T) columns, two carry passes applied (< QMAX + eps).
    All accumulator updates are full-width in-bounds slice-adds — the
    clipped-slice variant lowers to a scatter Pallas cannot stage."""
    T = a.shape[1]
    acc = jnp.zeros((52, T), dtype=jnp.uint32)
    for i in range(26):
        p = a[i][None, :] * b  # (26, T) 32-bit products
        plo = p & MASK
        phi = p >> 15
        acc = _acc_add(acc, plo, i)
        acc = _acc_add(acc, phi, i + 1)
        # column sums stay < 26 * 2^15.2 + carries < 2^21: no overflow
    return _compress1(_compress1(acc))


def _mont_kernel(a_ref, b_ref, p_ref, pp_ref, o_ref):
    a = a_ref[:]
    b = b_ref[:]
    pl_ = p_ref[:]
    pp = pp_ref[:]

    t = _wide_product(a, b)  # a*b
    # (t * P') mod 2^390: the low half of the full product (columns < 26
    # of the wide product are exactly the low product's columns)
    m = _wide_product(t[:26], pp)[:26]
    u = _wide_product(m, pl_)  # m*P
    s = t + u  # < 2^17.3 per column

    # full carry normalization: low 26 limbs vanish (divisible by R);
    # sequential chain over all 52 columns, carry as one lane row
    carry = jnp.zeros((a.shape[1],), dtype=jnp.uint32)
    out_rows = []
    for k in range(52):
        tcol = s[k] + carry
        carry = tcol >> 15
        if k >= 26:
            out_rows.append(tcol & MASK)
    o_ref[:] = jnp.stack(out_rows, axis=0)


@functools.lru_cache(maxsize=64)
def _mont_call(n_padded: int, tile: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n_padded // tile,)
    spec = pl.BlockSpec((26, tile), lambda i: (0, i),
                        memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((26, tile), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _mont_kernel,
        out_shape=jax.ShapeDtypeStruct((26, n_padded), jnp.uint32),
        grid=grid,
        in_specs=[spec, spec, const_spec, const_spec],
        out_specs=spec,
        interpret=interpret,
    )


def mont_mul_limbs(a_limbs, b_limbs, interpret: bool = False):
    """(26, N) x (26, N) quasi limbs -> (26, N) strict Montgomery product.
    Pads N up to a lane multiple; slices back."""
    n = a_limbs.shape[-1]
    tile = LANE_TILE if n >= LANE_TILE else max(128, -(-n // 128) * 128)
    n_padded = -(-n // tile) * tile
    if n_padded != n:
        pad = ((0, 0), (0, n_padded - n))
        a_limbs = jnp.pad(a_limbs, pad)
        b_limbs = jnp.pad(b_limbs, pad)
    p_tile = jnp.broadcast_to(
        jnp.asarray(_P_COLS, dtype=jnp.uint32), (26, tile)
    )
    pp_tile = jnp.broadcast_to(
        jnp.asarray(_PP_COLS, dtype=jnp.uint32), (26, tile)
    )
    out = _mont_call(n_padded, tile, interpret)(
        a_limbs, b_limbs, p_tile, pp_tile
    )
    return out[:, :n] if n_padded != n else out
