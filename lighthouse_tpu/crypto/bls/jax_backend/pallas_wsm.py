"""Fused weight-scalar-mul step kernels (LIGHTHOUSE_TPU_WSM-gated).

After the fused Miller loop landed (pallas_miller.py, measured +17-35%
on chip), the 64-bit weight scalar multiplications became the dispatch
leader: `points.scalar_mul_bits` runs a 64-step `lax.scan` whose body
issues ~7 stacked `pallas_call` groups per curve (double 7 muls, add 16,
selects) — ~900 dispatches per batch verify against the Miller loop's
~126.  Here each double-and-always-add step runs as ONE Mosaic program
per curve: Jacobian double + MIXED add (the base point arrives affine,
so Z2=1 drops 5 of the generic add's 16 muls) + bit/infinity selects,
every intermediate in VMEM under pallas_miller's in-kernel lazy-bound
discipline (KFp / k2_*).  64 steps -> 128 dispatches for both curves.

The mixed-add formulas compute the exact same Jacobian representative
as `points._raw_add` specialised to Z2=1 (U1=X1, S1=Y1, W-Z1Z1-Z2Z2 =
2*Z1), so the fused path is value-identical coordinate-wise, not just
equivalent-as-a-point — the differential tests assert canonical
equality of X, Y, Z and the infinity flags.

Capability twin of blst's scalar multiplication inside
`verify_multiple_aggregate_signatures` (crypto/bls/src/impls/blst.rs:
35-117); the batching/weights design is backend.py's.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import fp as F
from . import pallas_fp as PF
from .pallas_miller import (
    N_CONSTS,
    _const_arrays,
    _Ctx,
    _pad_flat,
    KFp,
    k2_add,
    k2_dbl,
    k2_guard,
    k2_mul,
    k2_neg,
    k2_reduce,
    k2_select,
    k2_sqr,
    k2_sub,
    kadd,
    kdbl,
    kguard,
    kmul,
    kneg,
    kreduce,
    kselect,
    ksqr,
    ksub,
)

N = F.N


# ---------------------------------------------------------------------------
# in-kernel field namespaces mirroring points.FP_OPS / FP2_OPS
# ---------------------------------------------------------------------------

class _K1:
    ncoords = 1

    @staticmethod
    def read(ins, base, i):
        return KFp(ins[base + i][:], 2.0)

    add = staticmethod(kadd)
    sub = staticmethod(ksub)
    dbl = staticmethod(kdbl)
    mul = staticmethod(kmul)
    sqr = staticmethod(ksqr)
    neg = staticmethod(kneg)
    reduce = staticmethod(kreduce)
    select = staticmethod(kselect)

    @staticmethod
    def guard(ctx, a, m: float = 11.0):
        return kguard(ctx, a, m)

    @staticmethod
    def lanes(v):
        return [v]


class _K2:
    ncoords = 2

    @staticmethod
    def read(ins, base, i):
        return (KFp(ins[base + 2 * i][:], 2.0),
                KFp(ins[base + 2 * i + 1][:], 2.0))

    add = staticmethod(k2_add)
    sub = staticmethod(k2_sub)
    dbl = staticmethod(k2_dbl)
    mul = staticmethod(k2_mul)
    sqr = staticmethod(k2_sqr)
    neg = staticmethod(k2_neg)
    reduce = staticmethod(k2_reduce)
    select = staticmethod(k2_select)

    @staticmethod
    def guard(ctx, a, m: float = 11.0):
        return k2_guard(ctx, a, m)

    @staticmethod
    def lanes(v):
        return [v[0], v[1]]


# ---------------------------------------------------------------------------
# in-kernel point formulas (points.py twins; see module docstring for the
# representative-equality argument)
# ---------------------------------------------------------------------------

def _k_jac_double(K, ctx, X, Y, Z):
    """points.jac_double, in-kernel: 7 muls/sqrs + carries."""
    A = K.sqr(ctx, X)
    B = K.sqr(ctx, Y)
    YZ = K.mul(ctx, Y, Z)
    E = K.add(ctx, K.dbl(ctx, A), A)
    XB = K.add(ctx, X, B)
    C = K.sqr(ctx, B)
    t = K.sqr(ctx, K.guard(ctx, XB))
    Fv = K.sqr(ctx, K.guard(ctx, E))
    D = K.dbl(ctx, K.sub(ctx, K.sub(ctx, t, A), C))
    X3 = K.sub(ctx, Fv, K.dbl(ctx, D))
    m = K.mul(ctx, K.guard(ctx, E), K.guard(ctx, K.sub(ctx, D, X3)))
    C8 = K.dbl(ctx, K.dbl(ctx, K.dbl(ctx, C)))
    Y3 = K.sub(ctx, m, C8)
    Z3 = K.dbl(ctx, YZ)
    return (K.reduce(ctx, X3), K.reduce(ctx, Y3), K.reduce(ctx, Z3))


def _k_mixed_add(K, ctx, X1, Y1, Z1, x2, y2):
    """points._raw_add with Z2 = 1 (affine base): 11 muls/sqrs.

    Z2=1 makes Z2Z2=1, U1=X1, S1=Y1, and the Z3 pre-factor
    (Z1+Z2)^2 - Z1Z1 - Z2Z2 collapse to 2*Z1 — identical VALUES to the
    generic path, five fewer products.
    """
    Z1Z1 = K.sqr(ctx, Z1)
    U2 = K.mul(ctx, x2, Z1Z1)
    Z1cu = K.mul(ctx, Z1, Z1Z1)
    S2 = K.mul(ctx, y2, Z1cu)
    H = K.sub(ctx, U2, X1)
    rr = K.dbl(ctx, K.sub(ctx, S2, Y1))
    H2 = K.dbl(ctx, H)
    I = K.sqr(ctx, K.guard(ctx, H2))
    J = K.mul(ctx, K.guard(ctx, H), I)
    V = K.mul(ctx, X1, I)
    rr2 = K.sqr(ctx, K.guard(ctx, rr))
    X3 = K.sub(ctx, K.sub(ctx, rr2, J), K.dbl(ctx, V))
    m1 = K.mul(ctx, K.guard(ctx, rr), K.guard(ctx, K.sub(ctx, V, X3)))
    m2 = K.mul(ctx, Y1, J)
    Y3 = K.sub(ctx, m1, K.dbl(ctx, m2))
    Z3 = K.mul(ctx, K.dbl(ctx, Z1), K.guard(ctx, H))
    return (K.reduce(ctx, X3), K.reduce(ctx, Y3), K.reduce(ctx, Z3))


def _pt_select_lanes(K, mask, a_pt, b_pt):
    return tuple(K.select(mask, a, b) for a, b in zip(a_pt, b_pt))


def _make_step_kernel(K):
    """One double-and-always-add bit for one curve, flags included.

    refs in:  acc coords (3*ncoords planes), acc_inf (1,T),
              base affine (2*ncoords planes), base_inf (1,T),
              bit (1,T), one (the Montgomery 1 for Z of a lifted base),
              consts
    refs out: coords' (3*ncoords), inf' (1,T)
    """
    nc = K.ncoords
    n_acc = 3 * nc
    n_base = 2 * nc

    def kernel(*refs):
        n_in = n_acc + 1 + n_base + 1 + 1 + N_CONSTS
        ins, outs = refs[:n_in], refs[n_in:]
        ctx = _Ctx(ins[n_acc + 1 + n_base + 1 + 1:])
        acc = tuple(K.read(ins, 0, i) for i in range(3))
        inf_acc = ins[n_acc][:]           # (1, T) uint32
        base = tuple(K.read(ins, n_acc + 1, i) for i in range(2))
        inf_base = ins[n_acc + 1 + n_base][:]
        bit = ins[n_acc + 1 + n_base + 1][:]

        dbl_pt = _k_jac_double(K, ctx, *acc)
        add_pt = _k_mixed_add(K, ctx, *dbl_pt, *base)
        # jac_add_fast's flag discipline: base at infinity keeps the
        # doubled acc; acc at infinity takes the (lifted) base
        add_pt = _pt_select_lanes(K, inf_base, dbl_pt, add_pt)
        base_jac = (base[0], base[1], _base_z_one(K, ctx))
        add_pt = _pt_select_lanes(K, inf_acc, base_jac, add_pt)
        inf_add = inf_acc & inf_base

        out_pt = _pt_select_lanes(K, bit, add_pt, dbl_pt)
        inf_out = jnp.where(bit != 0, inf_add, inf_acc)

        lanes = []
        for v in out_pt:
            lanes += K.lanes(v)
        for ref, v in zip(outs[:n_acc], lanes):
            assert v.bound <= 2.0
            ref[:] = v.cols
        outs[n_acc][:] = inf_out

    return kernel


def _base_z_one(K, ctx):
    """Z = 1 (Montgomery one) for lifting the affine base to Jacobian."""
    if K.ncoords == 1:
        return KFp(ctx.one, 2.0)
    zero = KFp(ctx.one - ctx.one, 1.0)
    return (KFp(ctx.one, 2.0), zero)


@functools.lru_cache(maxsize=8)
def _step_call(ncoords: int, n_padded: int, tile: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K = _K1 if ncoords == 1 else _K2
    grid = (n_padded // tile,)
    spec = pl.BlockSpec((N, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    flag_spec = pl.BlockSpec((1, tile), lambda i: (0, i),
                             memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((N, tile), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    n_acc = 3 * ncoords
    n_base = 2 * ncoords
    in_specs = ([spec] * n_acc + [flag_spec] + [spec] * n_base
                + [flag_spec] + [flag_spec] + [const_spec] * N_CONSTS)
    out_shape = tuple(
        jax.ShapeDtypeStruct((N, n_padded), jnp.uint32)
        for _ in range(n_acc)
    ) + (jax.ShapeDtypeStruct((1, n_padded), jnp.uint32),)
    return pl.pallas_call(
        _make_step_kernel(K),
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=(spec,) * n_acc + (flag_spec,),
        interpret=interpret,
    )


def scalar_mul_bits_fused(ops, p_aff, inf_base, wbits):
    """[k]P per lane, fused step kernels; drop-in for
    `points.scalar_mul_bits(ops, from_affine(ops, p_aff), wbits)`.

    ``p_aff``: affine (x, y) field elements (LFp / fp2 pairs);
    ``inf_base``: (*batch,) bool — lanes whose base is the identity;
    ``wbits``: (nbits, *batch) MSB-first scalar bits.
    Returns a Jacobian point tuple exactly like scalar_mul_bits.
    """
    from . import points as P

    ncoords = ops.ncoords
    interpret = jax.default_backend() != "tpu"

    def pin(c):
        return F.relabel(F.guard_le(c, 2.0), 2.0)

    coords = [pin(c) for xy in p_aff for c in ops.lanes(xy)]
    batch = F.batch_shape(coords[0])

    def flat(x: F.LFp):
        return x.limbs.reshape(N, -1)

    base_lanes = [flat(c) for c in coords]
    n = base_lanes[0].shape[-1]
    tile = PF.pick_tile(n)

    one = F.one_like(coords[0])
    zero = F.zero_like(coords[0])
    # acc starts at pt_infinity_like: (one, one, zero) + flag set
    acc_lanes = ([flat(one)] * ncoords + [flat(one)] * ncoords
                 + [flat(zero)] * ncoords)
    inf_acc = jnp.ones((1, n), dtype=jnp.uint32)
    inf_b = jnp.asarray(inf_base, dtype=jnp.uint32).reshape(1, -1)

    all_in, n0, n_padded = _pad_flat(
        acc_lanes + [inf_acc] + base_lanes + [inf_b], tile
    )
    n_acc = 3 * ncoords
    acc_arr = jnp.stack(all_in[:n_acc])
    inf_acc_p = all_in[n_acc]
    base_arr = jnp.stack(all_in[n_acc + 1:n_acc + 1 + 2 * ncoords])
    inf_b_p = all_in[-1]

    call = _step_call(ncoords, n_padded, tile, interpret)
    consts = _const_arrays(tile)
    bits = wbits.reshape(wbits.shape[0], -1).astype(jnp.uint32)
    bits = jnp.pad(bits, ((0, 0), (0, n_padded - n0))) if n_padded != n0 \
        else bits

    def step(carry, bit):
        acc_arr, inf_acc_p = carry
        outs = call(*[acc_arr[i] for i in range(n_acc)], inf_acc_p,
                    *[base_arr[i] for i in range(2 * ncoords)], inf_b_p,
                    bit.reshape(1, -1), *consts)
        return (jnp.stack(outs[:n_acc]), outs[n_acc]), None

    (acc_arr, inf_acc_p), _ = jax.lax.scan(step, (acc_arr, inf_acc_p), bits)

    def unflat(i):
        return F.LFp(acc_arr[i][:, :n0].reshape((N,) + batch), 2.0)

    out_coords = [unflat(i) for i in range(n_acc)]
    pt = tuple(
        ops.unlanes(out_coords[i * ncoords:(i + 1) * ncoords])
        for i in range(3)
    )
    inf_out = inf_acc_p[0, :n0].reshape(batch).astype(bool)
    return pt + (inf_out,)
