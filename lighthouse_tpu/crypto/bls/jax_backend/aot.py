"""AOT executable store: serialize the staged BLS programs, warm-boot nodes.

ROADMAP item 4's operational half.  A long-running node compiles a
handful of programs once and streams batches through them — but every
boot and every upgrade re-pays that compile (170 s for the pallas
chains, worse for pathological compositions).  This module makes the
compiled artifacts themselves durable:

* :class:`AotStore` — an on-disk store under ``<datadir>/aot_cache/``:
  one ``jax.export`` StableHLO blob per staged program, keyed by the
  same ``program_fingerprint`` the ``jit.compile`` spans carry (kernel
  entry point x static config x jax version x device kind), indexed by
  a signed JSON ``manifest.json``.  Capture is a side effect of normal
  operation: the backend's ``traced_jit`` first-call hook exports each
  program right after its compile, so a node that has served traffic
  has, by construction, a store describing its working set.
* :func:`prewarm` — the ``bn --prewarm`` boot phase: deserialize and
  install every current manifest entry into the backend's kernel cache
  (``prewarm.load`` spans, ``aot_cache_hits_total``), and optionally
  trace-compile the misses, BEFORE the node joins the network or the
  serve front door opens.  A prewarmed process performs zero tracing
  compiles of staged programs on its serving path.

Integrity posture (never-raise): a corrupt, truncated, tampered or
version-mismatched entry can only cost the time to detect it — ``load``
falls back to returning None (the caller trace-compiles as if the store
were cold) and counts the event in ``aot_cache_rejects_total``.  The
manifest is signed (sha256 over a domain-separated canonical encoding)
so partial writes and hand-edits are detected as a unit; each blob is
content-addressed by its own sha256 recorded in the manifest.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field

from ....obs.tracer import TRACER
from ....utils import device_kind, get_logger, log_with
from ....utils.metrics import (
    AOT_CACHE_HITS,
    AOT_CACHE_MISSES,
    AOT_CACHE_REJECTS,
)

log = get_logger("aot")

MANIFEST_SCHEMA = 1

# Domain separator for the manifest signature: sha256 over this prefix +
# the canonical (sorted-keys, compact) JSON of the entries table.  Not a
# MAC — there is no secret; the signature detects truncation, partial
# writes and accidental edits as a unit, the same trust model as the
# per-blob content hashes.
MANIFEST_DOMAIN = "lighthouse-tpu/aot-manifest/v1:"

# The registered program set eligible for AOT capture from the serving
# path: the batch-verify kernels (both h2c modes) and the rare-path
# aggregate kernel.  Keep this a literal tuple — the ``aot-manifest``
# registry-lint family AST-parses it and cross-references (a) every name
# here against the kernel definitions in backend.py and (b) every
# manifest entry's ``kernel`` field against this set (orphans are
# findings in both directions).
AOT_KERNELS = (
    "_verify_kernel",
    "_verify_kernel_h2c",
    "_aggregate_verify_kernel",
)


def manifest_signature(entries: dict) -> str:
    """Deterministic signature over a manifest table (see
    MANIFEST_DOMAIN) — used for both the ``entries`` index and the
    autotuned ``plan`` (each carries its own signature, so tampering
    with either is detected independently).  Shared with the
    ``aot-manifest`` / ``tune-plan`` lint families so an audited
    manifest is checked with the byte-identical algorithm."""
    blob = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256((MANIFEST_DOMAIN + blob).encode()).hexdigest()


# Sentinel for _write_manifest: "keep whatever plan the manifest already
# holds" (capture must not drop a tuned plan when it re-signs entries).
_KEEP_PLAN = object()


_EXPORT_TYPES_REGISTERED = [False]


def register_export_types() -> None:
    """Register the backend's custom pytree containers with
    ``jax.export``'s serialization registry (idempotent).  The staged
    programs close over :class:`~.fp.LFp` operands; without this,
    ``Exported.serialize`` refuses the pytree."""
    if _EXPORT_TYPES_REGISTERED[0]:
        return
    from jax import export

    from . import fp as F

    try:
        export.register_pytree_node_serialization(
            F.LFp,
            serialized_name="lighthouse_tpu.LFp",
            serialize_auxdata=lambda bound: json.dumps(bound).encode(),
            deserialize_auxdata=lambda b: json.loads(bytes(b).decode()),
        )
    except ValueError:
        pass  # a previous registration (e.g. module reload) already holds
    _EXPORT_TYPES_REGISTERED[0] = True


def _abstractify(args):
    """Shape/dtype skeleton of the call args: export re-traces from
    avals only, so this never touches buffer contents — safe even when
    the originals were donated to the compiled call."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args
    )


class AotStore:
    """Signed on-disk store of exported (AOT-serialized) staged programs.

    Layout under ``root``::

        manifest.json        signed index: fingerprint -> entry meta
        <fingerprint>.bin    jax.export StableHLO blob, content-hashed

    Every read path is never-raise: a broken store behaves exactly like
    a cold one (plus a rejects counter and a structured log line)."""

    def __init__(self, root: str):
        self.root = root
        self.manifest_path = os.path.join(root, "manifest.json")

    # -- manifest ----------------------------------------------------------

    def entries(self) -> dict:
        """The signature-verified entries table; ``{}`` (plus one reject
        count) when the manifest is absent-after-claiming, corrupt,
        truncated, or its signature does not match."""
        if not os.path.exists(self.manifest_path):
            return {}
        try:
            with open(self.manifest_path, encoding="utf-8") as f:
                doc = json.load(f)
            entries = doc["entries"]
            if doc.get("schema") != MANIFEST_SCHEMA:
                raise ValueError(f"manifest schema {doc.get('schema')!r}")
            if doc.get("signature") != manifest_signature(entries):
                raise ValueError("manifest signature mismatch")
            return entries
        except Exception as exc:  # noqa: BLE001 — never-raise read path
            AOT_CACHE_REJECTS.inc()
            log_with(log, 30, "AOT manifest rejected",
                     path=self.manifest_path, error=str(exc))
            return {}

    def _read_doc(self) -> dict:
        """Raw manifest document, ``{}`` on any problem (never-raise;
        signature checks happen in :meth:`entries` / :meth:`plan`)."""
        if not os.path.exists(self.manifest_path):
            return {}
        try:
            with open(self.manifest_path, encoding="utf-8") as f:
                return json.load(f)
        except Exception:  # noqa: BLE001 — never-raise read path
            return {}

    def plan(self) -> dict:
        """The signature-verified autotuned kernel plan
        (autotune.tune's output: device kind × jax version × per-shape
        winning arms), or ``{}`` when absent, corrupt, or tampered —
        a bad plan behaves exactly like a cold boot."""
        doc = self._read_doc()
        plan = doc.get("plan")
        if not isinstance(plan, dict) or not plan:
            return {}
        if doc.get("plan_signature") != manifest_signature(plan):
            AOT_CACHE_REJECTS.inc()
            log_with(log, 30, "AOT plan rejected",
                     path=self.manifest_path, error="plan signature mismatch")
            return {}
        return plan

    def write_plan(self, plan: dict | None) -> None:
        """Persist (or clear, with ``None``) the autotuned plan alongside
        the entries table.  The entries index is re-read and re-signed
        through the verified path, so a tuner can never launder a
        tampered entries table by writing a plan."""
        os.makedirs(self.root, exist_ok=True)
        self._write_manifest(self.entries(), plan=plan or None)

    def _write_manifest(self, entries: dict, plan=_KEEP_PLAN) -> None:
        if plan is _KEEP_PLAN:
            # capture() rewrites entries; keep the tuned plan it rides
            # with — but only if the plan still verifies (a tampered
            # plan must not get re-signed into legitimacy).
            held = self.plan()
            plan = held or None
        doc = {
            "schema": MANIFEST_SCHEMA,
            "entries": entries,
            "signature": manifest_signature(entries),
        }
        if plan:
            doc["plan"] = plan
            doc["plan_signature"] = manifest_signature(plan)
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True, indent=1)
        os.replace(tmp, self.manifest_path)

    # -- capture (export + serialize) --------------------------------------

    def capture(self, call, cache_key, args, kernel: str = "") -> bool:
        """Export + serialize a just-compiled ``traced_jit`` program and
        record it under its fingerprint.  Runs on the serving path right
        after a first-call compile, so it must never raise: a failed
        capture costs the next boot a compile, nothing else."""
        try:
            import jax
            from jax import export

            register_export_types()
            fp_hex = call.fingerprint
            with TRACER.span("aot.capture", fingerprint=fp_hex,
                             kernel=kernel or "?"):
                exported = export.export(call.jitted)(*_abstractify(args))
                data = bytes(exported.serialize())
            os.makedirs(self.root, exist_ok=True)
            blob_name = fp_hex + ".bin"
            with open(os.path.join(self.root, blob_name), "wb") as f:
                f.write(data)
            entries = self.entries()
            entries[fp_hex] = {
                "kernel": kernel or getattr(call, "kernel", ""),
                "cache_key": list(cache_key),
                "jax": jax.__version__,
                "backend": jax.default_backend(),
                "blob": blob_name,
                "sha256": hashlib.sha256(data).hexdigest(),
                "size": len(data),
            }
            self._write_manifest(entries)
            log_with(log, 20, "AOT program captured", fingerprint=fp_hex,
                     kernel=kernel, bytes=len(data))
            return True
        except Exception as exc:  # noqa: BLE001 — capture is best-effort
            log_with(log, 30, "AOT capture failed",
                     kernel=kernel, error=str(exc))
            return False

    # -- load (deserialize) ------------------------------------------------

    def load(self, fingerprint: str, meta: dict | None = None):
        """Deserialize one entry into a callable, or None (never raises).
        Counts ``aot_cache_hits_total`` on success, ``_misses_total``
        when the store simply has no such program, ``_rejects_total``
        when an entry exists but fails integrity or deserialization."""
        if meta is None:
            meta = self.entries().get(fingerprint)
        if meta is None:
            AOT_CACHE_MISSES.inc()
            return None
        try:
            from jax import export

            with open(os.path.join(self.root, meta["blob"]), "rb") as f:
                data = f.read()
            if hashlib.sha256(data).hexdigest() != meta["sha256"]:
                raise ValueError("blob sha256 mismatch")
            register_export_types()
            exported = export.deserialize(bytearray(data))
            AOT_CACHE_HITS.inc()
            return exported.call
        except Exception as exc:  # noqa: BLE001 — fall back to compiling
            AOT_CACHE_REJECTS.inc()
            log_with(log, 30, "AOT entry rejected", fingerprint=fingerprint,
                     error=str(exc))
            return None


# ---------------------------------------------------------------------------
# The --prewarm boot phase
# ---------------------------------------------------------------------------


@dataclass
class PrewarmReport:
    """What one prewarm pass did, for the boot log, the ``kind="boot"``
    bench row and the handoff scenario's SLO facts."""

    loaded: list = field(default_factory=list)     # installed fingerprints
    rejected: list = field(default_factory=list)   # failed integrity/deser
    stale: list = field(default_factory=list)      # other jax/backend/config
    compiled: list = field(default_factory=list)   # misses trace-compiled
    plan_shapes: int = 0                           # tuned shapes installed
    seconds: float = 0.0

    def to_row(self) -> dict:
        return {
            "loaded": len(self.loaded), "rejected": len(self.rejected),
            "stale": len(self.stale), "compiled": len(self.compiled),
            "plan_shapes": self.plan_shapes,
            "seconds": round(self.seconds, 3),
        }


def _entry_current(meta: dict, backend) -> bool:
    """Entry matches this process: same jax version + device kind, and —
    for verify-kernel entries whose cache key pins them — the backend's
    current h2c/mxu config (an entry for the other config would install
    into a cache slot the dispatcher never consults)."""
    import jax

    from . import fp as F

    if meta.get("jax") != jax.__version__:
        return False
    if meta.get("backend") != jax.default_backend():
        return False
    key = meta.get("cache_key") or ()
    if len(key) == 3 and key[0] != "agg":
        if bool(key[1]) != bool(getattr(backend, "device_h2c", key[1])):
            return False
        try:
            batch = int(key[0])
        except (TypeError, ValueError):
            return False
        # plan-aware: the arm the dispatcher will ask for at this batch
        # shape (installed tuned plan, unless an override forces one arm
        # for every shape — see fp.mxu_for_batch).
        if bool(key[2]) != F.mxu_for_batch(batch):
            return False
    return True


def prewarm(backend, store: AotStore, *, compile_misses: bool = False,
            ) -> PrewarmReport:
    """Deserialize and install every current manifest entry into
    ``backend``'s kernel cache, one ``prewarm.load`` span per entry.

    Runs BEFORE the node joins the network or the serve front door
    opens (cli.run_bn orders it ahead of every listener).  Entries for
    another jax version / device kind / backend config are skipped as
    stale (the fingerprint the backend would ask for differs anyway);
    corrupt entries are rejected by :meth:`AotStore.load` and — when
    ``compile_misses`` — re-compiled through the normal traced path so
    the store heals itself on the next capture.

    The autotuned kernel plan installs FIRST: entry currency is judged
    against the plan-resolved arm per batch shape, so the loaded set is
    exactly the programs the tuned dispatcher will ask for.  A stale or
    tampered plan installs nothing and the pass proceeds on the
    env/default arm (cold-plan behavior)."""
    report = PrewarmReport()
    t0 = time.perf_counter()
    plan = store.plan()
    if plan:
        from . import autotune

        report.plan_shapes = autotune.install_plan(plan)
    entries = store.entries()
    for fp_hex, meta in sorted(entries.items()):
        if not _entry_current(meta, backend):
            report.stale.append(fp_hex)
            AOT_CACHE_MISSES.inc()
            continue
        with TRACER.span("prewarm.load", fingerprint=fp_hex,
                         kernel=meta.get("kernel", "?")):
            call = store.load(fp_hex, meta)
        if call is None:
            report.rejected.append(fp_hex)
            if compile_misses and _recompile_entry(backend, meta):
                report.compiled.append(fp_hex)
            continue
        backend.install_kernel(tuple(meta.get("cache_key", ())),
                               fp_hex, call)
        report.loaded.append(fp_hex)
    report.seconds = time.perf_counter() - t0
    log_with(log, 20, "Prewarm finished", **report.to_row())
    return report


def _recompile_entry(backend, meta: dict) -> bool:
    """Trace-compile the program a rejected entry described, through the
    backend's normal (capturing) kernel path, so the store heals.  Only
    the batch-verify keys are recompilable from metadata alone."""
    key = meta.get("cache_key") or ()
    if len(key) != 3 or key[0] == "agg":
        return False
    try:
        warm = getattr(backend, "warm_compile", None)
        return bool(warm and warm(int(key[0])))
    except Exception as exc:  # noqa: BLE001 — prewarm must not kill boot
        log_with(log, 30, "Prewarm recompile failed",
                 cache_key=list(key), error=str(exc))
        return False


def record_boot_row(row: dict, path: str | None = None) -> None:
    """Append a ``kind="boot"`` row to BENCH_HISTORY.jsonl (the same
    ledger bench.py writes), never raising: boot accounting must not be
    able to fail a boot."""
    try:
        if path is None:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "..", "..", "..", "..", "BENCH_HISTORY.jsonl",
            )
        out = {
            "kind": "boot",
            "device_kind": device_kind(),
            "measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        out.update(row)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(out) + "\n")
    except Exception as exc:  # noqa: BLE001 — accounting only
        log_with(log, 30, "boot history write failed", error=str(exc))
