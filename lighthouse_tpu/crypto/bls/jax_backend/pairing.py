"""Batched optimal-ate pairing in JAX — the TPU Miller loop.

Mirrors the oracle's twist-based loop (bls/pairing.py: _line_dbl, _line_add,
final_exp_is_one) step for step: Jacobian line formulas on the M-twist,
sparse (w^0, w^2, w^3) line multiplication, conjugation for the negative BLS
parameter, and the cubed-hard-part final exponentiation via the identity
3*hard = (x-1)^2 (x+p) (x^2+p^2-1) + 3 (asserted in the oracle at import).

Reference semantics: one Miller loop per (pubkey, message) pair plus one for
the weighted signature aggregate, a single shared final exponentiation —
blst's verify_multiple_aggregate_signatures (crypto/bls/src/impls/blst.rs:
107-117, SURVEY.md §3.5).  Here the per-pair loops run vmapped-by-layout
(batch = trailing axis), the GT product is a log-depth tree reduction over
the batch axis, and the final exponentiation runs once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import params
from . import fp as F
from . import points as P
from . import tower as T

_X_BITS = [int(c) for c in bin(abs(params.X))[2:]]


def _line_dbl(Tpt, xp, yp):
    """Tangent line at Jacobian twist point, evaluated at P = (xp, yp) in
    Montgomery limb form.  Returns ((l0, l2, l3), 2T) — the JAX twin of the
    oracle's _line_dbl."""
    X1, Y1, Z1 = Tpt
    X_sq = T.fp2_sqr(X1)
    Y_sq = T.fp2_sqr(Y1)
    Z_sq = T.fp2_sqr(Z1)
    Z_cu = T.fp2_mul(Z_sq, Z1)
    l0 = T.fp2_sub(T.fp2_mul_small(T.fp2_mul(X_sq, X1), 3), T.fp2_dbl(Y_sq))
    l2 = T.fp2_neg(T.fp2_mul_fp(T.fp2_mul_small(T.fp2_mul(X_sq, Z_sq), 3), xp))
    l3 = T.fp2_mul_fp(T.fp2_dbl(T.fp2_mul(Y1, Z_cu)), yp)
    # Jacobian doubling reusing X_sq / Y_sq.
    C = T.fp2_sqr(Y_sq)
    D = T.fp2_dbl(
        T.fp2_sub(T.fp2_sub(T.fp2_sqr(T.fp2_add(X1, Y_sq)), X_sq), C)
    )
    E = T.fp2_mul_small(X_sq, 3)
    Fv = T.fp2_sqr(E)
    X3 = T.fp2_sub(Fv, T.fp2_dbl(D))
    Y3 = T.fp2_sub(T.fp2_mul(E, T.fp2_sub(D, X3)), T.fp2_mul_small(C, 8))
    Z3 = T.fp2_dbl(T.fp2_mul(Y1, Z1))
    return (l0, l2, l3), (X3, Y3, Z3)


def _line_add(Tpt, Q, xp, yp):
    """Chord line through Jacobian T and affine twist Q, evaluated at P.
    Returns ((l0, l2, l3), T + Q) — the JAX twin of the oracle's _line_add."""
    X1, Y1, Z1 = Tpt
    x2, y2 = Q
    Z_sq = T.fp2_sqr(Z1)
    Z_cu = T.fp2_mul(Z_sq, Z1)
    H = T.fp2_sub(T.fp2_mul(x2, Z_sq), X1)
    rr = T.fp2_sub(T.fp2_mul(y2, Z_cu), Y1)
    ZH = T.fp2_mul(Z1, H)
    l0 = T.fp2_sub(T.fp2_mul(rr, x2), T.fp2_mul(y2, ZH))
    l2 = T.fp2_neg(T.fp2_mul_fp(rr, xp))
    l3 = T.fp2_mul_fp(ZH, yp)
    H_sq = T.fp2_sqr(H)
    H_cu = T.fp2_mul(H, H_sq)
    V = T.fp2_mul(X1, H_sq)
    X3 = T.fp2_sub(T.fp2_sub(T.fp2_sqr(rr), H_cu), T.fp2_dbl(V))
    Y3 = T.fp2_sub(T.fp2_mul(rr, T.fp2_sub(V, X3)), T.fp2_mul(Y1, H_cu))
    return (l0, l2, l3), (X3, Y3, ZH)


def miller_loop(p_aff, q_aff):
    """Batched Miller loop over affine G1 points (xp, yp) and affine twist
    points ((x2c0,x2c1),(y2c0,y2c1)); trailing axes are the batch.  Neither
    input may be infinity (callers enforce this host-side, as the reference
    rejects infinity pubkeys/signatures before pairing)."""
    xp, yp = p_aff
    bits = jnp.array(_X_BITS[1:], dtype=jnp.uint32)
    T0 = (q_aff[0], q_aff[1], T.fp2_one_like(q_aff[0]))

    def step(carry, bit):
        f, Tpt = carry
        line, Tpt = _line_dbl(Tpt, xp, yp)
        f = T.fp12_mul_by_023(T.fp12_sqr(f), *line)
        line_a, T_add = _line_add(Tpt, q_aff, xp, yp)
        f_a = T.fp12_mul_by_023(f, *line_a)
        take = bit == 1
        f = jax.tree.map(lambda m, n: jnp.where(take, m, n), f_a, f)
        Tpt = P.pt_select(P.FP2_OPS, take, T_add, Tpt)
        return (f, Tpt), None

    f_init = _fp12_one_like_from_fp2(q_aff[0])
    (f, _), _ = lax.scan(step, (f_init, T0), bits)
    return T.fp12_conj(f)


def _fp12_one_like_from_fp2(x2):
    z = T.fp2_zero_like(x2)
    o = T.fp2_one_like(x2)
    return ((o, z, z), (z, z, z))


def gt_product(f):
    """Reduce the trailing batch axis of an fp12 pytree by multiplication
    (log-depth tree).  Batch must be along the last axis."""
    B = jax.tree.leaves(f)[0].shape[-1]
    # pad to a power of two with ones
    target = 1 << max(1, (B - 1).bit_length())
    if target != B:
        pad_one = _fp12_one_like_pad(f, target - B)
        f = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=-1), f, pad_one
        )
    n = target
    while n > 1:
        half = n // 2
        lo = jax.tree.map(lambda a: a[..., :half], f)
        hi = jax.tree.map(lambda a: a[..., half : 2 * half], f)
        f = T.fp12_mul(lo, hi)
        n = half
    return f


def _fp12_one_like_pad(f, count: int):
    ref = jax.tree.leaves(f)[0]
    shape = ref.shape[:-1] + (count,)
    zero = jnp.zeros(shape, dtype=ref.dtype)
    one_limbs = F.bcast(F.ONE_MONT, shape[1:])
    z2 = (zero, zero)
    o2 = (one_limbs, zero)
    return ((o2, z2, z2), (z2, z2, z2))


def final_exp_is_one(f):
    """Device twin of the oracle's final_exp_is_one: easy part, then the
    cubed hard part with 64-bit |x| exponentiations.  Returns bool(s) over
    the batch shape of f (normally scalar after gt_product)."""
    x = params.X
    # Easy part: f^(p^6-1) = conj(f) * f^-1, then ^(p^2+1).
    m = T.fp12_mul(T.fp12_conj(f), T.fp12_inv(f))
    m = T.fp12_mul(T.fp12_frobenius_n(m, 2), m)
    # m is now unit-norm: conjugation is inversion.
    a = _pow_signed(m, x - 1)
    a = _pow_signed(a, x - 1)
    b = T.fp12_mul(T.fp12_frobenius(a), _pow_signed(a, x))
    c = T.fp12_mul(
        T.fp12_mul(_pow_signed(_pow_signed(b, x), x), T.fp12_frobenius_n(b, 2)),
        T.fp12_conj(b),
    )
    out = T.fp12_mul(c, T.fp12_mul(T.fp12_sqr(m), m))
    return T.fp12_is_one(out)


def _pow_signed(a, e: int):
    """a^e on the cyclotomic subgroup (negative e via conjugation)."""
    if e < 0:
        return T.fp12_conj(T.fp12_pow(a, -e))
    return T.fp12_pow(a, e)


def pairing_check(p_aff, q_aff):
    """True iff prod_i e(P_i, Q_i) == 1 over the trailing batch axis."""
    f = miller_loop(p_aff, q_aff)
    return final_exp_is_one(gt_product(f))
