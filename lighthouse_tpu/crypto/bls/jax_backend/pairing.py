"""Batched optimal-ate pairing in JAX — the TPU Miller loop.

Mirrors the oracle's twist-based loop (bls/pairing.py: _line_dbl, _line_add,
final_exp_is_one) step for step: Jacobian line formulas on the M-twist,
sparse (w^0, w^2, w^3) line multiplication, conjugation for the negative BLS
parameter, and the cubed-hard-part final exponentiation via the identity
3*hard = (x-1)^2 (x+p) (x^2+p^2-1) + 3 (asserted in the oracle at import).

Reference semantics: one Miller loop per (pubkey, message) pair plus one for
the weighted signature aggregate, a single shared final exponentiation —
blst's verify_multiple_aggregate_signatures (crypto/bls/src/impls/blst.rs:
107-117, SURVEY.md §3.5).  Here the per-pair loops run batched-by-layout
(batch = trailing axis), the GT product is a log-depth tree reduction over
the batch axis, and the final exponentiation runs once.

Independent field products are grouped into stacked multiplies, and every
loop-carried value is reduced to the stable bound class at step boundaries
(see fp.py on the lazy representation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import params
from . import fp as F
from . import points as P
from . import tower as T

_X_BITS = [int(c) for c in bin(abs(params.X))[2:]]


def _line_dbl(Tpt, xp, yp):
    """Tangent line at Jacobian twist point, evaluated at P = (xp, yp) (LFp
    pair).  Returns ((l0, l2, l3), 2T), all coordinates reduced.  JAX twin
    of the oracle's _line_dbl."""
    X1, Y1, Z1 = Tpt
    X_sq, Y_sq, Z_sq, YZ = T.fp2_mul_many([X1, Y1, Z1, Y1], [X1, Y1, Z1, Z1])
    E = T.fp2_mul_small(X_sq, 3)
    XB = T.fp2_add(X1, Y_sq)
    X_cu, Z_cu, XZ, C, t, Fv = T.fp2_mul_many(
        [X_sq, Z_sq, X_sq, Y_sq, XB, E],
        [X1, Z1, Z_sq, Y_sq, XB, E],
    )
    l0 = T.fp2_sub(T.fp2_mul_small(X_cu, 3), T.fp2_dbl(Y_sq))
    D = T.fp2_dbl(T.fp2_sub(T.fp2_sub(t, X_sq), C))
    X3 = T.fp2_sub(Fv, T.fp2_dbl(D))
    YZ3 = T.fp2_dbl(T.fp2_mul(Y1, Z_cu))
    m3XZ = T.fp2_neg(T.fp2_mul_small(XZ, 3))
    # scalar evaluations at P: one stacked base multiply (4 lanes)
    ev = T.mm_many([m3XZ[0], m3XZ[1], YZ3[0], YZ3[1]], [xp, xp, yp, yp])
    l2 = (ev[0], ev[1])
    l3 = (ev[2], ev[3])
    (m,) = T.fp2_mul_many([E], [T.fp2_sub(D, X3)])
    Y3 = T.fp2_sub(m, T.fp2_mul_small(C, 8))
    Z3 = T.fp2_dbl(YZ)
    l0, l2, l3, X3, Y3, Z3 = _reduce_fp2_group([l0, l2, l3, X3, Y3, Z3])
    return ((l0, l2, l3), (X3, Y3, Z3))


def _line_add(Tpt, Q, xp, yp):
    """Chord line through Jacobian T and affine twist Q, evaluated at P.
    Returns ((l0, l2, l3), T + Q), reduced.  JAX twin of the oracle's
    _line_add."""
    X1, Y1, Z1 = Tpt
    x2, y2 = Q
    (Z_sq,) = T.fp2_mul_many([Z1], [Z1])
    Z_cu, U2 = T.fp2_mul_many([Z_sq, x2], [Z1, Z_sq])
    H = T.fp2_sub(U2, X1)
    S2, ZH, H_sq = T.fp2_mul_many([y2, Z1, H], [Z_cu, H, H])
    rr = T.fp2_sub(S2, Y1)
    p_rx, p_yZH, rr2, H_cu, V = T.fp2_mul_many(
        [rr, y2, rr, H, X1], [x2, ZH, rr, H_sq, H_sq]
    )
    l0 = T.fp2_sub(p_rx, p_yZH)
    X3 = T.fp2_sub(T.fp2_sub(rr2, H_cu), T.fp2_dbl(V))
    m1, m2 = T.fp2_mul_many([rr, Y1], [T.fp2_sub(V, X3), H_cu])
    Y3 = T.fp2_sub(m1, m2)
    neg_rr = T.fp2_neg(rr)
    ev = T.mm_many([neg_rr[0], neg_rr[1], ZH[0], ZH[1]], [xp, xp, yp, yp])
    l2 = (ev[0], ev[1])
    l3 = (ev[2], ev[3])
    l0, l2, l3, X3, Y3, Z3 = _reduce_fp2_group([l0, l2, l3, X3, Y3, ZH])
    return ((l0, l2, l3), (X3, Y3, Z3))


def _reduce_fp2_group(items):
    """Stacked reduction of a list of Fp2 values to stable bound 2."""
    lanes = []
    for it in items:
        lanes += [it[0], it[1]]
    red = T.reduce_many(lanes)
    return [(red[2 * i], red[2 * i + 1]) for i in range(len(items))]


def miller_loop(p_aff, q_aff):
    """Batched Miller loop over affine G1 points (xp, yp) and affine twist
    points ((x2c0,x2c1),(y2c0,y2c1)); trailing axes are the batch.  Neither
    input may be infinity (callers enforce this host-side, as the reference
    rejects infinity pubkeys/signatures before pairing)."""
    if F.miller_fused_active():
        from . import pallas_miller

        return pallas_miller.miller_loop_fused(p_aff, q_aff)
    def pin(c):
        return F.relabel(F.guard_le(c, 2.0), 2.0)

    xp, yp = pin(p_aff[0]), pin(p_aff[1])
    q_aff = (
        (pin(q_aff[0][0]), pin(q_aff[0][1])),
        (pin(q_aff[1][0]), pin(q_aff[1][1])),
    )
    bits = jnp.array(_X_BITS[1:], dtype=jnp.uint32)
    one2 = tuple(F.relabel(c, 2.0) for c in T.fp2_one_like(q_aff[0]))
    T0 = (q_aff[0], q_aff[1], one2)

    def step(carry, bit):
        f, Tpt = carry
        line, Tpt = _line_dbl(Tpt, xp, yp)
        f = T.fp12_mul_by_023(T.fp12_sqr(f), *line)
        line_a, T_add = _line_add(Tpt, q_aff, xp, yp)
        f_a = T.fp12_mul_by_023(f, *line_a)
        take = bit == 1
        f = T._map2_lfp(lambda m, n: F.fp_select(take, m, n), f_a, f)
        f = T.fp12_relabel(f, 2.0)
        Tsel = tuple(
            T.fp2_select(take, a, b) for a, b in zip(T_add, Tpt)
        )
        Tsel = tuple(
            (F.relabel(c[0], 2.0), F.relabel(c[1], 2.0)) for c in Tsel
        )
        return (f, Tsel), None

    f_init = T.fp12_relabel(_fp12_one_like_from_fp2(q_aff[0]), 2.0)
    (f, _), _ = lax.scan(step, (f_init, T0), bits)
    return T.fp12_conj(f)


def _fp12_one_like_from_fp2(x2):
    z = T.fp2_zero_like(x2)
    o = T.fp2_one_like(x2)
    return ((o, z, z), (z, z, z))


def gt_product(f):
    """Reduce the trailing batch axis of an fp12 pytree by multiplication
    (log-depth tree).  Batch must be along the last axis."""
    B = _fp12_batch(f)
    target = 1 << max(1, (B - 1).bit_length())
    if target != B:
        pad_one = _fp12_one_pad(f, target - B)
        f = T._map2_lfp(
            lambda a, b: F.LFp(
                jnp.concatenate([a.limbs, b.limbs], axis=-1),
                max(a.bound, b.bound),
            ),
            f,
            pad_one,
        )
    n = target
    while n > 1:
        half = n // 2
        lo = T._map_lfp(lambda a: F.LFp(a.limbs[..., :half], a.bound), f)
        hi = T._map_lfp(
            lambda a: F.LFp(a.limbs[..., half : 2 * half], a.bound), f
        )
        f = T.fp12_mul(lo, hi)
        n = half
    return f


def _fp12_batch(f):
    c = f[0][0][0]
    return c.limbs.shape[-1]


def _fp12_one_pad(f, count: int):
    ref = f[0][0][0]
    shape = ref.limbs.shape[:-1] + (count,)
    zero = F.LFp(jnp.zeros(shape, dtype=ref.limbs.dtype), 0.0)
    one = F.LFp(F.bcast(F.ONE_MONT, shape[1:]), 1.0)
    z2 = (zero, zero)
    o2 = (one, zero)
    return ((o2, z2, z2), (z2, z2, z2))


def final_exp_is_one(f):
    """Device twin of the oracle's final_exp_is_one: easy part, then the
    cubed hard part with 64-bit |x| exponentiations.  Returns bool(s) over
    the batch shape of f (normally scalar after gt_product)."""
    x = params.X
    # Easy part: f^(p^6-1) = conj(f) * f^-1, then ^(p^2+1).
    m = T.fp12_mul(T.fp12_conj(f), T.fp12_inv(f))
    m = T.fp12_mul(T.fp12_frobenius_n(m, 2), m)
    # m is now unit-norm: conjugation is inversion.
    a = _pow_signed(m, x - 1)
    a = _pow_signed(a, x - 1)
    b = T.fp12_mul(T.fp12_frobenius(a), _pow_signed(a, x))
    c = T.fp12_mul(
        T.fp12_mul(_pow_signed(_pow_signed(b, x), x), T.fp12_frobenius_n(b, 2)),
        T.fp12_conj(b),
    )
    out = T.fp12_mul(c, T.fp12_mul(T.fp12_sqr(m), m))
    return T.fp12_is_one(out)


def _pow_signed(a, e: int):
    """a^e on the cyclotomic subgroup (negative e via conjugation)."""
    if e < 0:
        return T.fp12_conj(T.fp12_pow(a, -e))
    return T.fp12_pow(a, e)


def pairing_check(p_aff, q_aff):
    """True iff prod_i e(P_i, Q_i) == 1 over the trailing batch axis."""
    f = miller_loop(p_aff, q_aff)
    return final_exp_is_one(gt_product(f))
