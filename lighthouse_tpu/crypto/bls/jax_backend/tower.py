"""Batched Fp2 / Fp6 / Fp12 tower in JAX, mirroring the oracle (fields.py).

Tower construction (identical to the oracle and to blst):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = u + 1
    Fp12 = Fp6[w] / (w^2 - v)

Elements are pytrees of limb arrays: Fp2 = (c0, c1), Fp6 = (c0, c1, c2) of
Fp2, Fp12 = (c0, c1) of Fp6 — so they thread through lax.scan carries and
jnp.where selections transparently.  Frobenius coefficients are taken from
the oracle's computed FROB_GAMMA table (never transcribed) and converted to
Montgomery limb constants at import.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import fields as _oracle
from .. import params
from . import fp as F

# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


def fp2(c0, c1):
    return (c0, c1)


def fp2_zero_like(x2):
    return (F.zero_like(x2[0]), F.zero_like(x2[0]))


def fp2_one_like(x2):
    return (F.one_like(x2[0]), F.zero_like(x2[0]))


def fp2_add(a, b):
    return (F.fp_add(a[0], b[0]), F.fp_add(a[1], b[1]))


def fp2_sub(a, b):
    return (F.fp_sub(a[0], b[0]), F.fp_sub(a[1], b[1]))


def fp2_neg(a):
    return (F.fp_neg(a[0]), F.fp_neg(a[1]))


def fp2_dbl(a):
    return fp2_add(a, a)


def fp2_mul(a, b):
    """Karatsuba: 3 base muls."""
    t0 = F.mont_mul(a[0], b[0])
    t1 = F.mont_mul(a[1], b[1])
    s = F.mont_mul(F.fp_add(a[0], a[1]), F.fp_add(b[0], b[1]))
    return (F.fp_sub(t0, t1), F.fp_sub(F.fp_sub(s, t0), t1))


def fp2_sqr(a):
    """(a0+a1 u)^2 = (a0-a1)(a0+a1) + 2 a0 a1 u — 2 base muls."""
    c0 = F.mont_mul(F.fp_sub(a[0], a[1]), F.fp_add(a[0], a[1]))
    t = F.mont_mul(a[0], a[1])
    return (c0, F.fp_add(t, t))


def fp2_mul_fp(a, s):
    """Multiply by an Fp element (limb array)."""
    return (F.mont_mul(a[0], s), F.mont_mul(a[1], s))


def fp2_mul_small(a, k: int):
    """Multiply by a small positive integer via doubling chains."""
    assert k >= 1
    out = a
    for bit in bin(k)[3:]:
        out = fp2_dbl(out)
        if bit == "1":
            out = fp2_add(out, a)
    return out


def fp2_conj(a):
    return (a[0], F.fp_neg(a[1]))


def fp2_mul_by_nonresidue(a):
    """Multiply by xi = 1 + u."""
    return (F.fp_sub(a[0], a[1]), F.fp_add(a[0], a[1]))


def fp2_inv(a):
    norm = F.fp_add(F.mont_sqr(a[0]), F.mont_sqr(a[1]))
    ninv = F.fp_inv(norm)
    return (F.mont_mul(a[0], ninv), F.fp_neg(F.mont_mul(a[1], ninv)))


def fp2_is_zero(a):
    return F.fp_is_zero(a[0]) & F.fp_is_zero(a[1])


def fp2_eq(a, b):
    return F.fp_eq(a[0], b[0]) & F.fp_eq(a[1], b[1])


def fp2_select(mask, a, b):
    return (F.fp_select(mask, a[0], b[0]), F.fp_select(mask, a[1], b[1]))


def fp2_const(oracle_fp2: "_oracle.Fp2", batch_shape):
    """Oracle Fp2 constant -> broadcast Montgomery limb pytree."""
    c0 = jnp.asarray(F.int_to_limbs(oracle_fp2.c0 * F.R_INT % F.P_INT))
    c1 = jnp.asarray(F.int_to_limbs(oracle_fp2.c1 * F.R_INT % F.P_INT))
    return (F.bcast(c0, batch_shape), F.bcast(c1, batch_shape))


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------


def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_zero_like(a):
    z = fp2_zero_like(a[0])
    return (z, z, z)


def fp6_one_like(a):
    return (fp2_one_like(a[0]), fp2_zero_like(a[0]), fp2_zero_like(a[0]))


def fp6_mul(a, b):
    """Toom/Karatsuba interpolation, as the oracle (fields.py Fp6.__mul__)."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(
        fp2_mul_by_nonresidue(
            fp2_sub(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), t1), t2)
        ),
        t0,
    )
    c1 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), t0), t1),
        fp2_mul_by_nonresidue(t2),
    )
    c2 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    return (fp2_mul_by_nonresidue(a[2]), a[0], a[1])


def fp6_mul_fp2(a, s):
    return tuple(fp2_mul(x, s) for x in a)


def fp6_inv(a):
    a0, a1, a2 = a
    t0 = fp2_sub(fp2_sqr(a0), fp2_mul_by_nonresidue(fp2_mul(a1, a2)))
    t1 = fp2_sub(fp2_mul_by_nonresidue(fp2_sqr(a2)), fp2_mul(a0, a1))
    t2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    denom = fp2_add(
        fp2_mul(a0, t0),
        fp2_add(
            fp2_mul_by_nonresidue(fp2_mul(a2, t1)),
            fp2_mul_by_nonresidue(fp2_mul(a1, t2)),
        ),
    )
    dinv = fp2_inv(denom)
    return (fp2_mul(t0, dinv), fp2_mul(t1, dinv), fp2_mul(t2, dinv))


def fp6_select(mask, a, b):
    return tuple(fp2_select(mask, x, y) for x, y in zip(a, b))


def fp6_eq(a, b):
    return fp2_eq(a[0], b[0]) & fp2_eq(a[1], b[1]) & fp2_eq(a[2], b[2])


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_one_like(a):
    return (fp6_one_like(a[0]), fp6_zero_like(a[0]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sqr(a):
    a0, a1 = a
    t = fp6_mul(a0, a1)
    c0 = fp6_sub(
        fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(a0, fp6_mul_by_v(a1))), t),
        fp6_mul_by_v(t),
    )
    return (c0, fp6_add(t, t))


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    denom = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    dinv = fp6_inv(denom)
    return (fp6_mul(a0, dinv), fp6_neg(fp6_mul(a1, dinv)))


def fp12_select(mask, a, b):
    return (fp6_select(mask, a[0], b[0]), fp6_select(mask, a[1], b[1]))


def fp12_eq(a, b):
    return fp6_eq(a[0], b[0]) & fp6_eq(a[1], b[1])


def fp12_is_one(a):
    return fp12_eq(a, fp12_one_like(a))


def fp12_mul_by_023(f, l0, l2, l3):
    """Sparse line multiplication, mirroring the oracle's Fp12.mul_by_023."""
    a0, a1 = f
    t0 = (
        fp2_add(fp2_mul(a0[0], l0), fp2_mul_by_nonresidue(fp2_mul(a0[2], l2))),
        fp2_add(fp2_mul(a0[0], l2), fp2_mul(a0[1], l0)),
        fp2_add(fp2_mul(a0[1], l2), fp2_mul(a0[2], l0)),
    )
    t1 = (
        fp2_mul_by_nonresidue(fp2_mul(a1[2], l3)),
        fp2_mul(a1[0], l3),
        fp2_mul(a1[1], l3),
    )
    s = fp6_add(a0, a1)
    l23 = fp2_add(l2, l3)
    t2 = (
        fp2_add(fp2_mul(s[0], l0), fp2_mul_by_nonresidue(fp2_mul(s[2], l23))),
        fp2_add(fp2_mul(s[0], l23), fp2_mul(s[1], l0)),
        fp2_add(fp2_mul(s[1], l23), fp2_mul(s[2], l0)),
    )
    return (fp6_add(t0, fp6_mul_by_v(t1)), fp6_sub(fp6_sub(t2, t0), t1))


# Frobenius: coefficients from the oracle's computed table.


def _gamma(i: int, batch_shape):
    return fp2_const(_oracle.FROB_GAMMA[i], batch_shape)


def fp12_frobenius(a):
    bs = a[0][0][0].shape[1:]
    c0, c1 = a
    f0 = (
        fp2_conj(c0[0]),
        fp2_mul(fp2_conj(c0[1]), _gamma(2, bs)),
        fp2_mul(fp2_conj(c0[2]), _gamma(4, bs)),
    )
    g1 = _gamma(1, bs)
    f1 = (
        fp2_mul(fp2_conj(c1[0]), g1),
        fp2_mul(fp2_mul(fp2_conj(c1[1]), _gamma(2, bs)), g1),
        fp2_mul(fp2_mul(fp2_conj(c1[2]), _gamma(4, bs)), g1),
    )
    return (f0, f1)


def fp12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fp12_frobenius(a)
    return a


def fp12_pow(a, e: int):
    """a^e for a static non-negative exponent; scan over bits."""
    import jax
    from jax import lax

    assert e >= 0
    if e == 0:
        return fp12_one_like(a)
    bits = jnp.array([int(c) for c in bin(e)[2:]], dtype=jnp.uint32)

    def step(acc, bit):
        acc = fp12_sqr(acc)
        withmul = fp12_mul(acc, a)
        take = bit == 1
        return jax.tree.map(lambda m, n: jnp.where(take, m, n), withmul, acc), None

    acc, _ = lax.scan(step, fp12_one_like(a), bits)
    return acc


def fp12_pow_signed(a, e: int, cyclotomic: bool = False):
    """a^e allowing negative e when a is unit-norm (conjugate == inverse)."""
    if e < 0:
        return fp12_conj(fp12_pow(a, -e))
    return fp12_pow(a, e)


# ---------------------------------------------------------------------------
# Host codecs (oracle <-> device)
# ---------------------------------------------------------------------------


def fp2_encode(vals: list["_oracle.Fp2"]) -> tuple:
    """Host: list of oracle Fp2 -> device Montgomery pytree, batch = len."""
    c0 = jnp.asarray(F.encode_mont([v.c0 for v in vals]))
    c1 = jnp.asarray(F.encode_mont([v.c1 for v in vals]))
    return (c0, c1)


def fp2_decode(x2) -> list["_oracle.Fp2"]:
    c0s = F.decode_mont(np.asarray(x2[0]))
    c1s = F.decode_mont(np.asarray(x2[1]))
    return [_oracle.Fp2(a, b) for a, b in zip(c0s, c1s)]


def fp12_encode(vals: list["_oracle.Fp12"]) -> tuple:
    c0 = tuple(fp2_encode([getattr(v.c0, c) for v in vals]) for c in ("c0", "c1", "c2"))
    c1 = tuple(fp2_encode([getattr(v.c1, c) for v in vals]) for c in ("c0", "c1", "c2"))
    return (c0, c1)


def fp12_decode(x12) -> list["_oracle.Fp12"]:
    c0 = [fp2_decode(x12[0][i]) for i in range(3)]
    c1 = [fp2_decode(x12[1][i]) for i in range(3)]
    out = []
    for j in range(len(c0[0])):
        out.append(
            _oracle.Fp12(
                _oracle.Fp6(c0[0][j], c0[1][j], c0[2][j]),
                _oracle.Fp6(c1[0][j], c1[1][j], c1[2][j]),
            )
        )
    return out
