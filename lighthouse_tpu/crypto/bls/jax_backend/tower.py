"""Batched Fp2 / Fp6 / Fp12 tower in JAX, mirroring the oracle (fields.py).

Tower construction (identical to the oracle and to blst):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = u + 1
    Fp12 = Fp6[w] / (w^2 - v)

Elements are pytrees of limb arrays: Fp2 = (c0, c1), Fp6 = (c0, c1, c2) of
Fp2, Fp12 = (c0, c1) of Fp6 — so they thread through lax.scan carries and
jnp.where selections transparently.

TPU-shaping: every multi-multiplication formula (Karatsuba products, the
sparse line multiply) funnels its independent base-field products through a
SINGLE ``mont_mul`` on batch-axis-concatenated operands ("horizontal
stacking").  One wide multiply instead of k narrow ones keeps the XLA graph
small (compile time) and the VPU lanes full (run time).  Additions and
subtractions are stacked the same way where they come in groups.

Frobenius coefficients are taken from the oracle's computed FROB_GAMMA table
(never transcribed) and converted to Montgomery limb constants at import.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import fields as _oracle
from . import fp as F

# ---------------------------------------------------------------------------
# Stacking helpers: k independent fp ops as one wide op
# ---------------------------------------------------------------------------


def _cat(xs):
    """Stack LFp lanes along the batch axis; bound = max (pessimistic)."""
    if len(xs) == 1:
        return xs[0]
    return F.LFp(
        jnp.concatenate([x.limbs for x in xs], axis=-1),
        max(x.bound for x in xs),
    )


def _split(x, k):
    if k == 1:
        return [x]
    b = x.limbs.shape[-1] // k
    return [
        F.LFp(x.limbs[..., i * b : (i + 1) * b], x.bound) for i in range(k)
    ]


def mm_many(As, Bs):
    """[a_i * b_i] via one Montgomery multiply on stacked lanes.  Lanes with
    oversized bounds are auto-reduced first (the stacked multiply's bound is
    the max over lanes, so one fat lane taxes them all)."""
    As = [F.guard_le(a, 40.0) for a in As]
    Bs = [F.guard_le(b, 40.0) for b in Bs]
    return _split(F.mont_mul(_cat(As), _cat(Bs)), len(As))


def add_many(As, Bs):
    return _split(F.fp_add(_cat(As), _cat(Bs)), len(As))


def sub_many(As, Bs):
    return _split(F.fp_sub(_cat(As), _cat(Bs)), len(As))


def reduce_many(xs):
    """Stacked value-preserving reduction: every element back to bound < 2."""
    return _split(F.fp_reduce(_cat(xs)), len(xs))


def fp2_reduce(a):
    c = reduce_many([a[0], a[1]])
    return (c[0], c[1])


def fp6_reduce(a):
    c = reduce_many([a[0][0], a[0][1], a[1][0], a[1][1], a[2][0], a[2][1]])
    return ((c[0], c[1]), (c[2], c[3]), (c[4], c[5]))


def _fp12_lanes(a):
    return [
        a[0][0][0], a[0][0][1], a[0][1][0], a[0][1][1], a[0][2][0], a[0][2][1],
        a[1][0][0], a[1][0][1], a[1][1][0], a[1][1][1], a[1][2][0], a[1][2][1],
    ]


def fp12_reduce(a):
    c = reduce_many(_fp12_lanes(a))
    return (
        ((c[0], c[1]), (c[2], c[3]), (c[4], c[5])),
        ((c[6], c[7]), (c[8], c[9]), (c[10], c[11])),
    )


# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


def fp2(c0, c1):
    return (c0, c1)


def fp2_zero_like(x2):
    return (F.zero_like(x2[0]), F.zero_like(x2[0]))


def fp2_one_like(x2):
    return (F.one_like(x2[0]), F.zero_like(x2[0]))


def fp2_add(a, b):
    c = add_many([a[0], a[1]], [b[0], b[1]])
    return (c[0], c[1])


def fp2_sub(a, b):
    c = sub_many([a[0], a[1]], [b[0], b[1]])
    return (c[0], c[1])


def fp2_neg(a):
    return (F.fp_neg(a[0]), F.fp_neg(a[1]))


def fp2_dbl(a):
    return fp2_add(a, a)


def fp2_guard(a, m: float = 11.0):
    """Auto-reduce an Fp2 operand whose coords exceed bound m (keeps the
    Karatsuba sum lanes inside mont_mul's input budget)."""
    if max(a[0].bound, a[1].bound) > m:
        return fp2_reduce(a)
    return a


def fp2_mul(a, b):
    """Karatsuba with one stacked base multiply (3 lanes)."""
    a, b = fp2_guard(a), fp2_guard(b)
    s = add_many([a[0], b[0]], [a[1], b[1]])  # a0+a1, b0+b1
    t0, t1, t2 = mm_many([a[0], a[1], s[0]], [b[0], b[1], s[1]])
    c = sub_many([t0, t2], [t1, F.fp_add(t0, t1)])
    return (c[0], c[1])


def fp2_sqr(a):
    """(a0-a1)(a0+a1), 2 a0 a1 — one stacked multiply (2 lanes)."""
    a = fp2_guard(a)
    d = F.fp_sub(a[0], a[1])
    s = F.fp_add(a[0], a[1])
    c0, t = mm_many([d, a[0]], [s, a[1]])
    return (c0, F.fp_add(t, t))


def fp2_mul_fp(a, s):
    c = mm_many([a[0], a[1]], [s, s])
    return (c[0], c[1])


def fp2_mul_small(a, k: int):
    """Multiply by a small positive integer via doubling chains."""
    assert k >= 1
    out = a
    for bit in bin(k)[3:]:
        out = fp2_dbl(out)
        if bit == "1":
            out = fp2_add(out, a)
    return out


def fp2_conj(a):
    return (a[0], F.fp_neg(a[1]))


def fp2_mul_by_nonresidue(a):
    """Multiply by xi = 1 + u."""
    c0 = F.fp_sub(a[0], a[1])
    c1 = F.fp_add(a[0], a[1])
    return (c0, c1)


def fp2_inv(a):
    a = fp2_guard(a)
    sq = mm_many([a[0], a[1]], [a[0], a[1]])
    norm = F.fp_add(sq[0], sq[1])
    ninv = F.fp_inv(norm)
    c = mm_many([a[0], a[1]], [ninv, ninv])
    return (c[0], F.fp_neg(c[1]))


def fp2_is_zero(a):
    return F.fp_is_zero(a[0]) & F.fp_is_zero(a[1])


def fp2_eq(a, b):
    return F.fp_eq(a[0], b[0]) & F.fp_eq(a[1], b[1])


def fp2_select(mask, a, b):
    return (F.fp_select(mask, a[0], b[0]), F.fp_select(mask, a[1], b[1]))


def fp2_const(oracle_fp2: "_oracle.Fp2", batch_shape):
    """Oracle Fp2 constant -> broadcast Montgomery limb pytree."""
    c0 = jnp.asarray(F.int_to_limbs(oracle_fp2.c0 * F.R_INT % F.P_INT))
    c1 = jnp.asarray(F.int_to_limbs(oracle_fp2.c1 * F.R_INT % F.P_INT))
    return (
        F.LFp(F.bcast(c0, batch_shape), 1.0),
        F.LFp(F.bcast(c1, batch_shape), 1.0),
    )


# ---------------------------------------------------------------------------
# Fp2 product stacking: k independent Fp2 multiplies in one base multiply
# ---------------------------------------------------------------------------


def fp2_mul_many(As, Bs):
    """[a_i * b_i] for Fp2 pairs via ONE stacked base multiply (3k lanes)."""
    k = len(As)
    if k == 1:
        return [fp2_mul(As[0], Bs[0])]
    As = [fp2_guard(a) for a in As]
    Bs = [fp2_guard(b) for b in Bs]
    # sums a0+a1 and b0+b1 for every pair: one stacked add
    sums = add_many(
        [a[0] for a in As] + [b[0] for b in Bs],
        [a[1] for a in As] + [b[1] for b in Bs],
    )
    a_sums, b_sums = sums[:k], sums[k:]
    lanes_a, lanes_b = [], []
    for a, b, sa, sb in zip(As, Bs, a_sums, b_sums):
        lanes_a += [a[0], a[1], sa]
        lanes_b += [b[0], b[1], sb]
    prods = mm_many(lanes_a, lanes_b)
    # combine per pair: c0 = t0 - t1 ; c1 = s - (t0 + t1)
    t0s = prods[0::3]
    t1s = prods[1::3]
    ss = prods[2::3]
    t01s = add_many(t0s, t1s)
    c0s = sub_many(t0s, t1s)
    c1s = sub_many(ss, t01s)
    return [(c0, c1) for c0, c1 in zip(c0s, c1s)]


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------


def fp6_add(a, b):
    c = add_many(
        [a[0][0], a[0][1], a[1][0], a[1][1], a[2][0], a[2][1]],
        [b[0][0], b[0][1], b[1][0], b[1][1], b[2][0], b[2][1]],
    )
    return ((c[0], c[1]), (c[2], c[3]), (c[4], c[5]))


def fp6_sub(a, b):
    c = sub_many(
        [a[0][0], a[0][1], a[1][0], a[1][1], a[2][0], a[2][1]],
        [b[0][0], b[0][1], b[1][0], b[1][1], b[2][0], b[2][1]],
    )
    return ((c[0], c[1]), (c[2], c[3]), (c[4], c[5]))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_zero_like(a):
    z = fp2_zero_like(a[0])
    return (z, z, z)


def fp6_one_like(a):
    return (fp2_one_like(a[0]), fp2_zero_like(a[0]), fp2_zero_like(a[0]))


def fp6_mul(a, b):
    """Toom/Karatsuba (as the oracle) with all six Fp2 products in one
    stacked base multiply."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    # pairwise sums for the cross terms: one stacked Fp2 add
    s = add_many(
        [a1[0], a1[1], b1[0], b1[1], a0[0], a0[1], b0[0], b0[1], a0[0], a0[1], b0[0], b0[1]],
        [a2[0], a2[1], b2[0], b2[1], a1[0], a1[1], b1[0], b1[1], a2[0], a2[1], b2[0], b2[1]],
    )
    a12, b12 = (s[0], s[1]), (s[2], s[3])
    a01, b01 = (s[4], s[5]), (s[6], s[7])
    a02, b02 = (s[8], s[9]), (s[10], s[11])
    t0, t1, t2, u12, u01, u02 = fp2_mul_many(
        [a0, a1, a2, a12, a01, a02], [b0, b1, b2, b12, b01, b02]
    )
    # c0 = xi*(u12 - t1 - t2) + t0
    # c1 = u01 - t0 - t1 + xi*t2
    # c2 = u02 - t0 - t2 + t1
    d1 = sub_many(
        [u12[0], u12[1], u01[0], u01[1], u02[0], u02[1]],
        [t1[0], t1[1], t0[0], t0[1], t0[0], t0[1]],
    )
    d2 = sub_many(
        [d1[0], d1[1], d1[2], d1[3], d1[4], d1[5]],
        [t2[0], t2[1], t1[0], t1[1], t2[0], t2[1]],
    )
    X = (d2[0], d2[1])  # u12 - t1 - t2
    Y = (d2[2], d2[3])  # u01 - t0 - t1
    Z = (d2[4], d2[5])  # u02 - t0 - t2
    xiX = fp2_mul_by_nonresidue(X)
    xit2 = fp2_mul_by_nonresidue(t2)
    e = add_many(
        [xiX[0], xiX[1], Y[0], Y[1], Z[0], Z[1]],
        [t0[0], t0[1], xit2[0], xit2[1], t1[0], t1[1]],
    )
    return fp6_reduce(((e[0], e[1]), (e[2], e[3]), (e[4], e[5])))


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    return (fp2_mul_by_nonresidue(a[2]), a[0], a[1])


def fp6_mul_fp2(a, s):
    c = fp2_mul_many([a[0], a[1], a[2]], [s, s, s])
    return (c[0], c[1], c[2])


def fp6_inv(a):
    a0, a1, a2 = a
    sq0, sq1, sq2, m12, m01, m02 = fp2_mul_many(
        [a0, a2, a1, a1, a0, a0], [a0, a2, a1, a2, a1, a2]
    )
    t0 = fp2_sub(sq0, fp2_mul_by_nonresidue(m12))
    t1 = fp2_sub(fp2_mul_by_nonresidue(sq1), m01)
    t2 = fp2_sub(sq2, m02)
    p0, p1, p2 = fp2_mul_many([a0, a2, a1], [t0, t1, t2])
    denom = fp2_add(
        p0, fp2_add(fp2_mul_by_nonresidue(p1), fp2_mul_by_nonresidue(p2))
    )
    dinv = fp2_inv(denom)
    c = fp2_mul_many([t0, t1, t2], [dinv, dinv, dinv])
    return fp6_reduce((c[0], c[1], c[2]))


def fp6_select(mask, a, b):
    return tuple(fp2_select(mask, x, y) for x, y in zip(a, b))


def fp6_eq(a, b):
    return fp2_eq(a[0], b[0]) & fp2_eq(a[1], b[1]) & fp2_eq(a[2], b[2])


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_one_like(a):
    return (fp6_one_like(a[0]), fp6_zero_like(a[0]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    u = fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1))
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(u, t0), t1)
    return fp12_reduce((c0, c1))


def fp12_sqr(a):
    a0, a1 = a
    t = fp6_mul(a0, a1)
    c0 = fp6_sub(
        fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(a0, fp6_mul_by_v(a1))), t),
        fp6_mul_by_v(t),
    )
    return fp12_reduce((c0, fp6_add(t, t)))


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    denom = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    dinv = fp6_inv(denom)
    return fp12_reduce((fp6_mul(a0, dinv), fp6_neg(fp6_mul(a1, dinv))))


def fp12_select(mask, a, b):
    return (fp6_select(mask, a[0], b[0]), fp6_select(mask, a[1], b[1]))


def fp12_eq(a, b):
    return fp6_eq(a[0], b[0]) & fp6_eq(a[1], b[1])


def fp12_is_one(a):
    return fp12_eq(a, fp12_one_like(a))


def fp12_mul_by_023(f, l0, l2, l3):
    """Sparse line multiplication (oracle: Fp12.mul_by_023) with all fifteen
    Fp2 products in one stacked base multiply."""
    a0, a1 = f
    s = fp6_add(a0, a1)
    l23 = fp2_add(l2, l3)
    prods = fp2_mul_many(
        [
            a0[0], a0[2], a0[0], a0[1], a0[1], a0[2],  # t0 terms
            a1[2], a1[0], a1[1],                        # t1 terms
            s[0], s[2], s[0], s[1], s[1], s[2],         # t2 terms
        ],
        [
            l0, l2, l2, l0, l2, l0,
            l3, l3, l3,
            l0, l23, l23, l0, l23, l0,
        ],
    )
    (p00, p02, q00, q01, r01, r02,
     w2, w0, w1,
     s00, s02, v00, v01, x01, x02) = prods
    t0 = (
        fp2_add(p00, fp2_mul_by_nonresidue(p02)),
        fp2_add(q00, q01),
        fp2_add(r01, r02),
    )
    t1 = (fp2_mul_by_nonresidue(w2), w0, w1)
    t2 = (
        fp2_add(s00, fp2_mul_by_nonresidue(s02)),
        fp2_add(v00, v01),
        fp2_add(x01, x02),
    )
    return fp12_reduce(
        (fp6_add(t0, fp6_mul_by_v(t1)), fp6_sub(fp6_sub(t2, t0), t1))
    )


# Frobenius: coefficients from the oracle's computed table.


def _gamma(i: int, batch_shape):
    return fp2_const(_oracle.FROB_GAMMA[i], batch_shape)


def fp12_frobenius(a):
    bs = F.batch_shape(a[0][0][0])
    c0, c1 = a
    g1 = _gamma(1, bs)
    g2 = _gamma(2, bs)
    g4 = _gamma(4, bs)
    g1g2 = fp2_mul(g2, g1)
    g1g4 = fp2_mul(g4, g1)
    m = fp2_mul_many(
        [fp2_conj(c0[1]), fp2_conj(c0[2]), fp2_conj(c1[0]), fp2_conj(c1[1]), fp2_conj(c1[2])],
        [g2, g4, g1, g1g2, g1g4],
    )
    f0 = (fp2_conj(c0[0]), m[0], m[1])
    f1 = (m[2], m[3], m[4])
    return fp12_reduce((f0, f1))


def fp12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fp12_frobenius(a)
    return a


def _map_lfp(f, x):
    """Apply f to every LFp leaf of a nested-tuple field element."""
    if isinstance(x, F.LFp):
        return f(x)
    return tuple(_map_lfp(f, c) for c in x)


def _map2_lfp(f, x, y):
    if isinstance(x, F.LFp):
        return f(x, y)
    return tuple(_map2_lfp(f, a, b) for a, b in zip(x, y))


def fp12_relabel(x, bound: float):
    """Pin every coordinate's static bound (upward only) — used to keep scan
    carries structurally stable."""
    return _map_lfp(lambda c: F.relabel(c, bound), x)


def fp12_pow(a, e: int):
    """a^e for a static non-negative exponent; scan over bits."""
    from jax import lax

    assert e >= 0
    if e == 0:
        return fp12_one_like(a)
    a = _map_lfp(lambda c: F.guard_le(c, 2.0), a)
    bits = jnp.array([int(c) for c in bin(e)[2:]], dtype=jnp.uint32)

    def step(acc, bit):
        acc = fp12_sqr(acc)
        withmul = fp12_mul(acc, a)
        take = bit == 1
        sel = _map2_lfp(lambda m, n: F.fp_select(take, m, n), withmul, acc)
        return fp12_relabel(sel, 2.0), None

    acc, _ = lax.scan(step, fp12_relabel(fp12_one_like(a), 2.0), bits)
    return acc


def fp12_pow_signed(a, e: int):
    """a^e allowing negative e when a is unit-norm (conjugate == inverse)."""
    if e < 0:
        return fp12_conj(fp12_pow(a, -e))
    return fp12_pow(a, e)


# ---------------------------------------------------------------------------
# Host codecs (oracle <-> device)
# ---------------------------------------------------------------------------


def fp2_encode(vals: list["_oracle.Fp2"]) -> tuple:
    """Host: list of oracle Fp2 -> device Montgomery pytree, batch = len."""
    return (F.lfp_encode([v.c0 for v in vals]), F.lfp_encode([v.c1 for v in vals]))


def fp2_decode(x2) -> list["_oracle.Fp2"]:
    c0s = F.decode_mont(x2[0])
    c1s = F.decode_mont(x2[1])
    return [_oracle.Fp2(a, b) for a, b in zip(c0s, c1s)]


def fp12_encode(vals: list["_oracle.Fp12"]) -> tuple:
    c0 = tuple(fp2_encode([getattr(v.c0, c) for v in vals]) for c in ("c0", "c1", "c2"))
    c1 = tuple(fp2_encode([getattr(v.c1, c) for v in vals]) for c in ("c0", "c1", "c2"))
    return (c0, c1)


def fp12_decode(x12) -> list["_oracle.Fp12"]:
    c0 = [fp2_decode(x12[0][i]) for i in range(3)]
    c1 = [fp2_decode(x12[1][i]) for i in range(3)]
    out = []
    for j in range(len(c0[0])):
        out.append(
            _oracle.Fp12(
                _oracle.Fp6(c0[0][j], c0[1][j], c0[2][j]),
                _oracle.Fp6(c1[0][j], c1[1][j], c1[2][j]),
            )
        )
    return out
