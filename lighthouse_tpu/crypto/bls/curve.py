"""Pure-Python BLS12-381 group arithmetic: G1 (over Fp) and G2 (over Fp2).

Reference-parity notes: this module provides the semantics the reference gets
from blst's point types — deserialization with validation (reference:
crypto/bls/src/generic_public_key.rs:70 infinity-pubkey rejection and blst
key_validate), subgroup checks (crypto/bls/src/impls/blst.rs:71-81), and the
Zcash compressed encodings used across the Ethereum ecosystem.

Points are affine `(x, y)` pairs of field elements or `None` for infinity;
hot loops (scalar mul, multi-exp) use Jacobian coordinates internally.
The coordinate field is generic: `Fp` for G1, `Fp2` for G2 — both expose the
same arithmetic interface (fields.py).
"""

from __future__ import annotations

from . import params
from .fields import Fp, Fp2

# ---------------------------------------------------------------------------
# Generic short-Weierstrass (a = 0) affine/Jacobian arithmetic
# ---------------------------------------------------------------------------
# A point is None (infinity) or (x, y) with y^2 = x^3 + b.
# A Jacobian point is (X, Y, Z): x = X/Z^2, y = Y/Z^3; infinity iff Z == 0.


def to_jacobian(pt, field):
    if pt is None:
        return (field.one(), field.one(), field.zero())
    return (pt[0], pt[1], field.one())


def from_jacobian(jpt, field):
    X, Y, Z = jpt
    if Z.is_zero():
        return None
    zinv = Z.inv()
    zinv2 = zinv.square()
    return (X * zinv2, Y * zinv2 * zinv)


def jac_double(pt, field):
    X, Y, Z = pt
    if Z.is_zero() or Y.is_zero():
        return (field.one(), field.one(), field.zero())
    A = X.square()
    B = Y.square()
    C = B.square()
    D = ((X + B).square() - A - C) * 2
    E = A * 3
    F = E.square()
    X3 = F - D * 2
    Y3 = E * (D - X3) - C * 8
    Z3 = (Y * Z) * 2
    return (X3, Y3, Z3)


def jac_add(p1, p2, field):
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if Z1.is_zero():
        return p2
    if Z2.is_zero():
        return p1
    Z1Z1 = Z1.square()
    Z2Z2 = Z2.square()
    U1 = X1 * Z2Z2
    U2 = X2 * Z1Z1
    S1 = Y1 * Z2 * Z2Z2
    S2 = Y2 * Z1 * Z1Z1
    if U1 == U2:
        if S1 == S2:
            return jac_double(p1, field)
        return (field.one(), field.one(), field.zero())
    H = U2 - U1
    I = (H * 2).square()
    J = H * I
    rr = (S2 - S1) * 2
    V = U1 * I
    X3 = rr.square() - J - V * 2
    Y3 = rr * (V - X3) - S1 * J * 2
    Z3 = ((Z1 + Z2).square() - Z1Z1 - Z2Z2) * H
    return (X3, Y3, Z3)


def jac_neg(pt):
    X, Y, Z = pt
    return (X, -Y, Z)


def jac_mul(pt, k: int, field):
    """Scalar multiplication (double-and-add, MSB first)."""
    if k < 0:
        return jac_mul(jac_neg(pt), -k, field)
    acc = (field.one(), field.one(), field.zero())
    if k == 0:
        return acc
    for bit in bin(k)[2:]:
        acc = jac_double(acc, field)
        if bit == "1":
            acc = jac_add(acc, pt, field)
    return acc


def affine_add(p1, p2, field):
    return from_jacobian(
        jac_add(to_jacobian(p1, field), to_jacobian(p2, field), field), field
    )


def affine_neg(pt):
    if pt is None:
        return None
    return (pt[0], -pt[1])


def affine_mul(pt, k: int, field):
    return from_jacobian(jac_mul(to_jacobian(pt, field), k, field), field)


def is_on_curve(pt, b, field) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y.square() == x.square() * x + b


# ---------------------------------------------------------------------------
# Concrete groups
# ---------------------------------------------------------------------------

B1 = Fp(params.B_G1)
B2 = Fp2(*params.B_G2)

G1_GENERATOR = (Fp(params.G1_GEN[0]), Fp(params.G1_GEN[1]))
G2_GENERATOR = (Fp2(*params.G2_GEN[0]), Fp2(*params.G2_GEN[1]))

assert is_on_curve(G1_GENERATOR, B1, Fp)
assert is_on_curve(G2_GENERATOR, B2, Fp2)


def _select_twist_order() -> int:
    """Pick the twist order among the six sextic-twist candidates by testing
    against random points of E'(Fp2).  (The G2 generator is useless for this:
    it has order R, which divides several candidates.)"""
    import random as _random

    rng = _random.Random(0x7157)
    samples = []
    while len(samples) < 4:
        x = Fp2(rng.randrange(params.P), rng.randrange(params.P))
        rhs = x.square() * x + B2
        y = rhs.sqrt()
        if y is not None:
            samples.append(to_jacobian((x, y), Fp2))
    for tt in params.TWIST_TRACE_CANDIDATES:
        n = params.P * params.P + 1 - tt
        if n % params.R != 0:
            continue
        if all(from_jacobian(jac_mul(s, n, Fp2), Fp2) is None for s in samples):
            return n
    raise AssertionError("no twist order candidate annihilates sample points")


N_E2 = _select_twist_order()
H2 = N_E2 // params.R

# The generators must be in the prime-order subgroups.
assert affine_mul(G1_GENERATOR, params.R, Fp) is None
assert affine_mul(G2_GENERATOR, params.R, Fp2) is None


def g1_subgroup_check(pt) -> bool:
    """Fast endomorphism-based membership test (endo.py), asserted there to
    equal the defining [r]P == inf check on random points."""
    from .endo import g1_subgroup_check_fast

    return g1_subgroup_check_fast(pt)


def g2_subgroup_check(pt) -> bool:
    from .endo import g2_subgroup_check_fast

    return g2_subgroup_check_fast(pt)


def g1_subgroup_check_slow(pt) -> bool:
    return affine_mul(pt, params.R, Fp) is None


def g2_subgroup_check_slow(pt) -> bool:
    return affine_mul(pt, params.R, Fp2) is None


def g1_clear_cofactor(pt):
    return affine_mul(pt, params.H1, Fp)


def g2_clear_cofactor(pt):
    return affine_mul(pt, H2, Fp2)


# ---------------------------------------------------------------------------
# Zcash compressed serialization
# ---------------------------------------------------------------------------
# Flag bits in the most significant byte of the encoding:
#   bit 7 (0x80): compressed flag (always set here)
#   bit 6 (0x40): infinity flag
#   bit 5 (0x20): sign of y (set if y is lexicographically the larger root)


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        out = bytearray(48)
        out[0] = 0xC0
        return bytes(out)
    x, y = pt
    data = bytearray(x.v.to_bytes(48, "big"))
    data[0] |= 0x80
    if y.v > (params.P - 1) // 2:
        data[0] |= 0x20
    return bytes(data)


def g1_from_bytes(data: bytes, subgroup_check: bool = True):
    """Deserialize a compressed G1 point.

    Raises ValueError on malformed input; returns None for the point at
    infinity. Mirrors blst deserialize + key_validate semantics (on-curve and
    subgroup checks; reference crypto/bls/src/impls/blst.rs:124-134).
    """
    if len(data) != 48:
        raise ValueError("G1 compressed encoding must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encodings not supported")
    infinity = bool(flags & 0x40)
    sign = bool(flags & 0x20)
    body = bytes([flags & 0x1F]) + data[1:]
    x_int = int.from_bytes(body, "big")
    if infinity:
        if sign or x_int != 0:
            raise ValueError("malformed infinity encoding")
        return None
    if x_int >= params.P:
        raise ValueError("x coordinate not in field")
    x = Fp(x_int)
    rhs = x.square() * x + B1
    y = rhs.sqrt()
    if y is None:
        raise ValueError("x is not on the curve")
    if (y.v > (params.P - 1) // 2) != sign:
        y = -y
    pt = (x, y)
    if subgroup_check and not g1_subgroup_check(pt):
        raise ValueError("point not in G1 subgroup")
    return pt


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        out = bytearray(96)
        out[0] = 0xC0
        return bytes(out)
    x, y = pt
    # c1 (the "imaginary" coefficient) is serialized first.
    data = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
    data[0] |= 0x80
    # Sign: lexicographic order on (c1, c0).
    if _fp2_lex_larger(y):
        data[0] |= 0x20
    return bytes(data)


def _fp2_lex_larger(y: Fp2) -> bool:
    """True if y > -y lexicographically on (c1, c0)."""
    ny1, ny0 = (-y).c1, (-y).c0
    return (y.c1, y.c0) > (ny1, ny0)


def g2_from_bytes(data: bytes, subgroup_check: bool = True):
    if len(data) != 96:
        raise ValueError("G2 compressed encoding must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encodings not supported")
    infinity = bool(flags & 0x40)
    sign = bool(flags & 0x20)
    body = bytes([flags & 0x1F]) + data[1:48]
    x_c1 = int.from_bytes(body, "big")
    x_c0 = int.from_bytes(data[48:], "big")
    if infinity:
        if sign or x_c1 != 0 or x_c0 != 0:
            raise ValueError("malformed infinity encoding")
        return None
    if x_c0 >= params.P or x_c1 >= params.P:
        raise ValueError("x coordinate not in field")
    x = Fp2(x_c0, x_c1)
    rhs = x.square() * x + B2
    y = rhs.sqrt()
    if y is None:
        raise ValueError("x is not on the curve")
    if _fp2_lex_larger(y) != sign:
        y = -y
    pt = (x, y)
    if subgroup_check and not g2_subgroup_check(pt):
        raise ValueError("point not in G2 subgroup")
    return pt
