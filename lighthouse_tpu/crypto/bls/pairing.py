"""Pure-Python optimal-ate pairing for BLS12-381.

Reference parity: this is the semantic model of what blst's
`verify_multiple_aggregate_signatures` computes per pair — N Miller loops plus
one shared final exponentiation (reference: crypto/bls/src/impls/blst.rs:107-117
and SURVEY.md §3.5).  The JAX/TPU backend reimplements the same math with
limb-vectorized kernels; this module is the differential-test oracle.

Implementation choice: G2 points are *untwisted* into E(Fp12) and the Miller
loop runs generically over Fp12 with affine line evaluations.  That is slow
(Python bignums) but transparently correct: vertical-line denominators lie in
Fp6 (the untwisted x-coordinates have no w-component), so they are erased by
the final exponentiation and can be omitted — the classical denominator
elimination that makes the M-twist convenient.
"""

from __future__ import annotations

from . import params
from .fields import Fp, Fp2, Fp6, Fp12, XI

# Loop count: |x|, MSB-first bit string.
_X_ABS = abs(params.X)
_X_BITS = bin(_X_ABS)[2:]

_XI_INV = XI.inv()


def untwist(q):
    """Map an affine point of E'(Fp2) (the M-twist) to E(Fp12).

    (x', y') -> (x' / w^2, y' / w^3)  with  1/w^2 = xi^{-1} v^2  and
    1/w^3 = xi^{-1} v w  in the tower basis.
    """
    if q is None:
        return None
    x2, y2 = q
    x12 = Fp12(Fp6(Fp2.zero(), Fp2.zero(), x2 * _XI_INV), Fp6.zero())
    y12 = Fp12(Fp6.zero(), Fp6(Fp2.zero(), y2 * _XI_INV, Fp2.zero()))
    return (x12, y12)


def embed_g1(p):
    """Embed an affine G1 point (Fp coords) into E(Fp12)."""
    if p is None:
        return None
    x, y = p
    return (
        Fp12(Fp6(Fp2(x.v, 0), Fp2.zero(), Fp2.zero()), Fp6.zero()),
        Fp12(Fp6(Fp2(y.v, 0), Fp2.zero(), Fp2.zero()), Fp6.zero()),
    )


def miller_loop(p_g1, q_g2) -> Fp12:
    """f_{|x|,Q}(P) (conjugated for the negative BLS parameter), without the
    final exponentiation.  `p_g1` is an affine G1 point, `q_g2` an affine G2
    (twist) point; either may be None (infinity), yielding 1."""
    if p_g1 is None or q_g2 is None:
        return Fp12.one()
    xp, yp = embed_g1(p_g1)
    Q = untwist(q_g2)
    xq, yq = Q
    f = Fp12.one()
    xt, yt = xq, yq
    for bit in _X_BITS[1:]:
        # Tangent line at T, evaluated at P.
        slope = (xt.square() * 3) * (yt * 2).inv()
        line = yp - yt - slope * (xp - xt)
        f = f.square() * line
        # T = 2T (affine doubling via the same slope).
        x_new = slope.square() - xt * 2
        y_new = slope * (xt - x_new) - yt
        xt, yt = x_new, y_new
        if bit == "1":
            # Chord line through T and Q, evaluated at P.
            slope = (yq - yt) * (xq - xt).inv()
            line = yp - yt - slope * (xp - xt)
            f = f * line
            x_new = slope.square() - xt - xq
            y_new = slope * (xt - x_new) - yt
            xt, yt = x_new, y_new
    # x < 0: f_{-n} ≡ conj(f_n) modulo the final exponentiation.
    return f.conjugate()


# Hard-part exponent (p^4 - p^2 + 1) / r, computed once.
_P = params.P
_HARD_EXP, _hard_rem = divmod(_P**4 - _P**2 + 1, params.R)
assert _hard_rem == 0


def final_exponentiation(f: Fp12) -> Fp12:
    """f^((p^12-1)/r): easy part via Frobenius/conjugation, hard part as a
    plain square-and-multiply (reference oracle; the JAX backend uses the
    cyclotomic x-chain, differentially tested against this)."""
    # Easy part: f^(p^6-1) then ^(p^2+1).
    f = f.conjugate() * f.inv()
    f = f.frobenius_n(2) * f
    # Hard part.
    return f.pow(_HARD_EXP)


def multi_miller_loop(pairs) -> Fp12:
    f = Fp12.one()
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return f


def pairing(p, q) -> Fp12:
    return final_exponentiation(miller_loop(p, q))


def pairing_check(pairs) -> bool:
    """True iff prod e(P_i, Q_i) == 1."""
    return final_exponentiation(multi_miller_loop(pairs)) == Fp12.one()
