"""Pure-Python optimal-ate pairing for BLS12-381.

Reference parity: this is the semantic model of what blst's
`verify_multiple_aggregate_signatures` computes per pair — N Miller loops plus
one shared final exponentiation (reference: crypto/bls/src/impls/blst.rs:107-117
and SURVEY.md §3.5).  The JAX/TPU backend reimplements the same math with
limb-vectorized kernels; this module is the differential-test oracle.

Two Miller loops are provided:

* `miller_loop` (the default) — the fast, twist-based loop: the G2 point stays
  on E'(Fp2) in Jacobian coordinates, line functions are evaluated directly in
  the sparse basis Fp12 = Fp2[w]/(w^6 - xi) at positions (w^0, w^2, w^3), and
  inversion-free formulas absorb all denominators into Fp2/Fp4 factors that
  the final exponentiation erases.  This is the structure the JAX/TPU kernels
  mirror step for step.
* `miller_loop_untwisted` — the original transparent implementation that
  untwists G2 into E(Fp12) and runs affine formulas generically.  It is the
  oracle's oracle: tests assert the two agree after final exponentiation.
"""

from __future__ import annotations

from . import params
from .fields import Fp, Fp2, Fp6, Fp12, XI, fp12_from_fp2_coeffs

# Loop count: |x|, MSB-first bit string.
_X_ABS = abs(params.X)
_X_BITS = bin(_X_ABS)[2:]

_XI_INV = XI.inv()


def untwist(q):
    """Map an affine point of E'(Fp2) (the M-twist) to E(Fp12).

    (x', y') -> (x' / w^2, y' / w^3)  with  1/w^2 = xi^{-1} v^2  and
    1/w^3 = xi^{-1} v w  in the tower basis.
    """
    if q is None:
        return None
    x2, y2 = q
    x12 = Fp12(Fp6(Fp2.zero(), Fp2.zero(), x2 * _XI_INV), Fp6.zero())
    y12 = Fp12(Fp6.zero(), Fp6(Fp2.zero(), y2 * _XI_INV, Fp2.zero()))
    return (x12, y12)


def embed_g1(p):
    """Embed an affine G1 point (Fp coords) into E(Fp12)."""
    if p is None:
        return None
    x, y = p
    return (
        Fp12(Fp6(Fp2(x.v, 0), Fp2.zero(), Fp2.zero()), Fp6.zero()),
        Fp12(Fp6(Fp2(y.v, 0), Fp2.zero(), Fp2.zero()), Fp6.zero()),
    )


def _line_dbl(T, xp_v: int, yp_v: int):
    """Tangent line at Jacobian twist point T, evaluated at P = (xp, yp),
    scaled by 2*Y*Z^3 (an Fp2 factor, erased by the final exponentiation) and
    by w^3 (an Fp4 factor, likewise erased).  Returns the sparse coefficients
    (l0, l2, l3) at w^0/w^2/w^3 and the doubled point.

    Derivation (slope lam = 3x^2/2y, x = X/Z^2, y = Y/Z^3):
      l*w^3 = yp*w^3 - lam*xp*w^2 + (lam*x - y)*w^0 ; multiply by 2YZ^3:
      l0 = 3X^3 - 2Y^2,  l2 = -3X^2Z^2*xp,  l3 = 2YZ^3*yp.
    """
    X1, Y1, Z1 = T
    X_sq = X1.square()
    Y_sq = Y1.square()
    Z_sq = Z1.square()
    Z_cu = Z_sq * Z1
    l0 = X_sq * X1 * 3 - Y_sq * 2
    l2 = -(X_sq * Z_sq * 3) * xp_v
    l3 = (Y1 * Z_cu * 2) * yp_v
    # Jacobian doubling (a = 0), reusing X_sq / Y_sq.
    C = Y_sq.square()
    D = ((X1 + Y_sq).square() - X_sq - C) * 2
    E = X_sq * 3
    F = E.square()
    X3 = F - D * 2
    Y3 = E * (D - X3) - C * 8
    Z3 = (Y1 * Z1) * 2
    return (l0, l2, l3), (X3, Y3, Z3)


def _line_add(T, Q, xp_v: int, yp_v: int):
    """Chord line through Jacobian T and affine twist Q, evaluated at P,
    scaled by Z*H (Fp2, erased) and w^3.  Returns ((l0, l2, l3), T + Q).

    With U2 = x2 Z^2, S2 = y2 Z^3, H = U2 - X, r = S2 - Y, lam = r/(Z*H):
      l0 = r*x2 - y2*Z*H,  l2 = -r*xp,  l3 = Z*H*yp.
    """
    X1, Y1, Z1 = T
    x2, y2 = Q
    Z_sq = Z1.square()
    Z_cu = Z_sq * Z1
    H = x2 * Z_sq - X1
    rr = y2 * Z_cu - Y1
    ZH = Z1 * H
    l0 = rr * x2 - y2 * ZH
    l2 = -rr * xp_v
    l3 = ZH * yp_v
    # Mixed Jacobian + affine addition via the same H / rr.
    H_sq = H.square()
    H_cu = H * H_sq
    V = X1 * H_sq
    X3 = rr.square() - H_cu - V * 2
    Y3 = rr * (V - X3) - Y1 * H_cu
    Z3 = ZH
    return (l0, l2, l3), (X3, Y3, Z3)


def _sparse_to_fp12(l0: Fp2, l2: Fp2, l3: Fp2) -> Fp12:
    return fp12_from_fp2_coeffs([l0, Fp2.zero(), l2, l3, Fp2.zero(), Fp2.zero()])


def miller_loop(p_g1, q_g2) -> Fp12:
    """Twist-based Miller loop: f_{|x|,Q}(P) conjugated for the negative BLS
    parameter, up to Fp2/Fp4 scalings erased by the final exponentiation.
    `p_g1` is an affine G1 point, `q_g2` an affine G2 (twist) point; either
    may be None (infinity), yielding 1."""
    if p_g1 is None or q_g2 is None:
        return Fp12.one()
    xp_v, yp_v = p_g1[0].v, p_g1[1].v
    T = (q_g2[0], q_g2[1], Fp2.one())
    f = Fp12.one()
    for bit in _X_BITS[1:]:
        line, T = _line_dbl(T, xp_v, yp_v)
        f = f.square().mul_by_023(*line)
        if bit == "1":
            line, T = _line_add(T, q_g2, xp_v, yp_v)
            f = f.mul_by_023(*line)
    return f.conjugate()


def miller_loop_untwisted(p_g1, q_g2) -> Fp12:
    """f_{|x|,Q}(P) (conjugated for the negative BLS parameter), without the
    final exponentiation.  `p_g1` is an affine G1 point, `q_g2` an affine G2
    (twist) point; either may be None (infinity), yielding 1."""
    if p_g1 is None or q_g2 is None:
        return Fp12.one()
    xp, yp = embed_g1(p_g1)
    Q = untwist(q_g2)
    xq, yq = Q
    f = Fp12.one()
    xt, yt = xq, yq
    for bit in _X_BITS[1:]:
        # Tangent line at T, evaluated at P.
        slope = (xt.square() * 3) * (yt * 2).inv()
        line = yp - yt - slope * (xp - xt)
        f = f.square() * line
        # T = 2T (affine doubling via the same slope).
        x_new = slope.square() - xt * 2
        y_new = slope * (xt - x_new) - yt
        xt, yt = x_new, y_new
        if bit == "1":
            # Chord line through T and Q, evaluated at P.
            slope = (yq - yt) * (xq - xt).inv()
            line = yp - yt - slope * (xp - xt)
            f = f * line
            x_new = slope.square() - xt - xq
            y_new = slope * (xt - x_new) - yt
            xt, yt = x_new, y_new
    # x < 0: f_{-n} ≡ conj(f_n) modulo the final exponentiation.
    return f.conjugate()


# Hard-part exponent (p^4 - p^2 + 1) / r, computed once.
_P = params.P
_HARD_EXP, _hard_rem = divmod(_P**4 - _P**2 + 1, params.R)
assert _hard_rem == 0


def final_exponentiation(f: Fp12) -> Fp12:
    """f^((p^12-1)/r): easy part via Frobenius/conjugation, hard part as a
    plain square-and-multiply (reference oracle; the JAX backend uses the
    cyclotomic x-chain, differentially tested against this)."""
    # Easy part: f^(p^6-1) then ^(p^2+1).
    f = f.conjugate() * f.inv()
    f = f.frobenius_n(2) * f
    # Hard part.
    return f.pow(_HARD_EXP)


def final_exp_is_one(f: Fp12) -> bool:
    """Fast check  f^((p^12-1)/r) == 1  via the cubed hard part.

    Uses the BLS12 identity  3*hard = (x-1)^2 (x+p) (x^2+p^2-1) + 3
    (asserted below): since gcd(3, r) = 1, f^(easy*3*hard) == 1 iff
    f^(easy*hard) == 1.  Exponentiations by x are 64-bit, so this is ~2x
    cheaper than the generic 381-bit hard-part pow — and it is the exact
    structure the JAX backend's final exponentiation mirrors.
    """
    x = params.X
    # Easy part: f^((p^6-1)(p^2+1)).
    m = f.conjugate() * f.inv()
    m = m.frobenius_n(2) * m
    # Cubed hard part.
    a = m.pow(x - 1)
    a = a.pow(x - 1)
    b = a.frobenius() * a.pow(x)  # a^(x+p)
    # b is in the cyclotomic subgroup (it is a power of m, which satisfies
    # m^(p^6+1) = 1), so conjugation is inversion.
    c = b.pow(x).pow(x) * b.frobenius_n(2) * b.conjugate()  # b^(x^2+p^2-1)
    return c * m.square() * m == Fp12.one()


assert 3 * _HARD_EXP == (params.X - 1) ** 2 * (params.X + _P) * (
    params.X**2 + _P**2 - 1
) + 3


def multi_miller_loop(pairs) -> Fp12:
    f = Fp12.one()
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return f


def pairing(p, q) -> Fp12:
    """Exact pairing value (uses the transparent untwisted loop so that the
    result is the canonical e(P, Q), free of the twist-loop's scalings)."""
    return final_exponentiation(miller_loop_untwisted(p, q))


def pairing_check(pairs) -> bool:
    """True iff prod e(P_i, Q_i) == 1."""
    return final_exp_is_one(multi_miller_loop(pairs))
