"""Endomorphism-accelerated G1/G2 operations for BLS12-381.

The reference gets these from blst's hand-written assembly (subgroup checks at
crypto/bls/src/impls/blst.rs:71-81 via blst's `in_group`, cofactor clearing
inside hash-to-curve).  Here they are derived from first principles and
verified at import time against the slow scalar-multiplication definitions:

* psi — the untwist-Frobenius-twist endomorphism of E'(Fp2).  On G2 it acts
  as multiplication by the BLS parameter x (because p ≡ x (mod r) for BLS12
  curves), which gives Scott's fast subgroup test  psi(Q) == [x]Q  (a 64-bit
  scalar mul instead of a 255-bit one).
* phi — the GLV endomorphism (x, y) -> (beta*x, y) of E(Fp).  On G1 it acts
  as multiplication by lambda = x^2 - 1 (lambda^2 + lambda + 1 = 0 mod r),
  giving the fast G1 test  phi(P) == [x^2 - 1]P  with two 64-bit muls.
* clear_cofactor_fast — Budroni-Pintore G2 cofactor clearing
  [x^2-x-1]P + [x-1]psi(P) + psi2([2]P), equal to multiplication by the
  RFC 9380 effective cofactor h_eff (asserted on random twist points).

All constants are computed here from params.P / params.X, never transcribed.
"""

from __future__ import annotations

import random as _random

from . import params
from .curve import (
    Fp,
    Fp2,
    B1,
    B2,
    G1_GENERATOR,
    G2_GENERATOR,
    affine_add,
    affine_mul,
    from_jacobian,
    is_on_curve,
    jac_add,
    jac_mul,
    to_jacobian,
)
from .fields import XI

P = params.P
X = params.X

# ---------------------------------------------------------------------------
# psi: untwist-Frobenius-twist on E'(Fp2)
# ---------------------------------------------------------------------------
# With w^6 = xi and the untwist (x, y) -> (x/w^2, y/w^3), Frobenius acts on w
# as w^p = gamma * w, gamma = xi^((p-1)/6).  Twisting back:
#   psi(x, y) = (conj(x) * gamma^-2, conj(y) * gamma^-3).

assert (P - 1) % 6 == 0
_GAMMA = XI.pow((P - 1) // 6)
PSI_CX = _GAMMA.pow(2).inv()
PSI_CY = _GAMMA.pow(3).inv()


def psi(pt):
    """The G2 endomorphism; pt is an affine E'(Fp2) point (or None)."""
    if pt is None:
        return None
    x, y = pt
    return (x.conjugate() * PSI_CX, y.conjugate() * PSI_CY)


def psi2(pt):
    return psi(psi(pt))


# psi must be an endomorphism of E' acting as [x] on G2.
assert is_on_curve(psi(G2_GENERATOR), B2, Fp2)
assert psi(G2_GENERATOR) == affine_mul(G2_GENERATOR, X, Fp2)

# ---------------------------------------------------------------------------
# phi: GLV endomorphism on E(Fp)
# ---------------------------------------------------------------------------
# beta is a primitive cube root of unity in Fp; phi(x,y) = (beta x, y) acts on
# G1 as multiplication by an eigenvalue lambda with lambda^2+lambda+1 = 0
# (mod r).  lambda = x^2 - 1 satisfies this for BLS12 ((x^2-1)^2 + (x^2-1) + 1
# = x^4 - x^2 + 1 = Phi_12(x), divisible by r).  The two cube roots give the
# two eigenvalues; pick the one matching lambda = x^2 - 1.

assert (P - 1) % 3 == 0
LAMBDA = X * X - 1
assert (LAMBDA * LAMBDA + LAMBDA + 1) % params.R == 0


def _find_beta() -> int:
    rng = _random.Random(0xBE7A)
    while True:
        g = rng.randrange(2, P)
        b = pow(g, (P - 1) // 3, P)
        if b != 1:
            return b


_B_CAND = _find_beta()


def _phi_with(beta: int, pt):
    if pt is None:
        return None
    x, y = pt
    return (x * beta, y)


# Select the cube root whose action on G1 is [x^2 - 1].
_target = affine_mul(G1_GENERATOR, LAMBDA, Fp)
if _phi_with(_B_CAND, G1_GENERATOR) == _target:
    BETA = _B_CAND
else:
    _other = _B_CAND * _B_CAND % P
    assert _phi_with(_other, G1_GENERATOR) == _target, "no cube root acts as lambda"
    BETA = _other


def phi(pt):
    """The G1 endomorphism (x, y) -> (beta x, y)."""
    return _phi_with(BETA, pt)


# ---------------------------------------------------------------------------
# Fast subgroup checks (Scott, "A note on group membership tests…", 2021)
# ---------------------------------------------------------------------------


def g1_subgroup_check_fast(pt) -> bool:
    """P in G1  iff  phi(P) == [x^2 - 1]P == [x-1]([x+1]P)."""
    if pt is None:
        return True
    t = affine_mul(affine_mul(pt, X + 1, Fp), X - 1, Fp)
    return phi(pt) == t


def g2_subgroup_check_fast(pt) -> bool:
    """Q in G2  iff  psi(Q) == [x]Q  (p ≡ x mod r on the r-torsion)."""
    if pt is None:
        return True
    return psi(pt) == affine_mul(pt, X, Fp2)


# ---------------------------------------------------------------------------
# Fast G2 cofactor clearing (Budroni-Pintore)
# ---------------------------------------------------------------------------
#   h(P) = [x^2 - x - 1]P + [x - 1]psi(P) + psi2([2]P)
# which equals multiplication by the RFC 9380 effective cofactor h_eff.


def clear_cofactor_fast(pt):
    if pt is None:
        return None
    xP = affine_mul(pt, X, Fp2)  # [x]P
    x2P = affine_mul(xP, X, Fp2)  # [x^2]P
    # [x^2]P - [x]P - P
    acc = to_jacobian(x2P, Fp2)
    acc = jac_add(acc, jac_mul(to_jacobian(xP, Fp2), -1, Fp2), Fp2)
    acc = jac_add(acc, jac_mul(to_jacobian(pt, Fp2), -1, Fp2), Fp2)
    # + [x-1]psi(P)
    psiP = psi(pt)
    acc = jac_add(acc, jac_mul(to_jacobian(psiP, Fp2), X - 1, Fp2), Fp2)
    # + psi2([2]P)
    acc = jac_add(acc, to_jacobian(psi2(affine_add(pt, pt, Fp2)), Fp2), Fp2)
    return from_jacobian(acc, Fp2)


def _selfcheck_endo() -> None:
    """Verify the fast paths against the slow definitions on random points."""
    from .hash_to_curve import H_EFF_G2

    rng = _random.Random(0xE4D0)
    # Random E'(Fp2) points (almost surely NOT in G2).
    pts = []
    while len(pts) < 2:
        x = Fp2(rng.randrange(P), rng.randrange(P))
        rhs = x.square() * x + B2
        y = rhs.sqrt()
        if y is not None:
            pts.append((x, y))
    for pt in pts:
        cleared = clear_cofactor_fast(pt)
        assert cleared == affine_mul(pt, H_EFF_G2, Fp2)
        # fast check matches the defining [r]Q == inf test
        slow = affine_mul(pt, params.R, Fp2) is None
        assert g2_subgroup_check_fast(pt) == slow
        assert g2_subgroup_check_fast(cleared)
        assert affine_mul(cleared, params.R, Fp2) is None
    # Random E(Fp) points: fast G1 check vs the defining [r]P == inf test.
    g1_pts = []
    while len(g1_pts) < 2:
        xv = Fp(rng.randrange(P))
        y = (xv.square() * xv + B1).sqrt()
        if y is not None:
            g1_pts.append((xv, y))
    for pt in g1_pts:
        slow = affine_mul(pt, params.R, Fp) is None
        assert g1_subgroup_check_fast(pt) == slow
        in_g1 = affine_mul(pt, params.H1, Fp)
        assert g1_subgroup_check_fast(in_g1)
        assert affine_mul(in_g1, params.R, Fp) is None


_selfcheck_endo()
