"""RFC 9380 hash-to-curve for BLS12-381 G2: BLS12381G2_XMD:SHA-256_SSWU_RO_.

This is the `Hash_to_G2` the reference obtains from blst (DST constant at
reference crypto/bls/src/impls/blst.rs:13).  Pipeline:

    msg --expand_message_xmd(SHA-256)--> 512 bytes
        --hash_to_field--> u0, u1 in Fp2
        --SSWU--> two points on E' (the 3-isogenous auxiliary curve)
        --isogeny--> two points on E2 (the twist), added
        --clear_cofactor--> G2

SHA-256 runs host-side (hashlib).  The field/curve/pairing layers this feeds
have JAX twins in jax_backend/ (fp.py, tower.py, points.py, pairing.py); the
SSWU map itself currently runs host-side.  The isogeny constants are derived,
not transcribed — see tools/derive_g2_isogeny.py and g2_isogeny.py.
"""

from __future__ import annotations

import hashlib

from . import g2_isogeny, params
from .curve import B2, affine_add, affine_mul
from .fields import Fp2

# SSWU parameters for the auxiliary curve E' (RFC 9380 §8.8.2).
A_PRIME = Fp2(0, 240)
B_PRIME = Fp2(1012, 1012)
Z = Fp2(-2 % params.P, -1 % params.P)  # -(2 + u)

_L = 64  # bytes per field-element limb draw (ceil((381 + 128) / 8))
_HASH_BLOCK = 64  # SHA-256 block size


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + 31) // 32
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(_HASH_BLOCK)
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = bytearray(b)
    prev = b
    for i in range(2, ell + 1):
        xored = bytes(x ^ y for x, y in zip(b0, prev))
        prev = hashlib.sha256(xored + bytes([i]) + dst_prime).digest()
        out += prev
    return bytes(out[:len_in_bytes])


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = params.DST) -> list[Fp2]:
    """RFC 9380 §5.2 hash_to_field with m=2, L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off : off + _L], "big") % params.P)
        out.append(Fp2(coords[0], coords[1]))
    return out


def sswu(u: Fp2):
    """Simplified SWU map to the auxiliary curve E' (RFC 9380 §6.6.2)."""
    # tv = Z * u^2;  x1 = -B/A * (1 + 1/(tv^2 + tv))  (or B/(Z*A) if zero)
    tv = Z * u.square()
    tv2 = tv.square() + tv
    if tv2.is_zero():
        x1 = B_PRIME * (Z * A_PRIME).inv()
    else:
        x1 = (-B_PRIME) * A_PRIME.inv() * (Fp2.one() + tv2.inv())
    gx1 = (x1.square() + A_PRIME) * x1 + B_PRIME
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = tv * x1
        gx2 = (x2.square() + A_PRIME) * x2 + B_PRIME
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square (impossible)"
        x, y = x2, y2
    if u.sgn0() != y.sgn0():
        y = -y
    return (x, y)


# Isogeny coefficient tables as Fp2 (low degree first).
#
# Y_NUM is negated relative to the raw Velu derivation: the derivation's
# scaling isomorphism used c = 1/3, but the RFC 9380 §8.8.2 map corresponds to
# c = -1/3 (same c^2, negated c^3) — i.e. the RFC map composes the normalized
# Velu isogeny with the [-1] automorphism on the y-coordinate. Verified
# against the RFC 9380 J.10.1 test vector.
_X_NUM = [Fp2(c0, c1) for c0, c1 in g2_isogeny.X_NUM]
_X_DEN = [Fp2(c0, c1) for c0, c1 in g2_isogeny.X_DEN]
_Y_NUM = [-Fp2(c0, c1) for c0, c1 in g2_isogeny.Y_NUM]
_Y_DEN = [Fp2(c0, c1) for c0, c1 in g2_isogeny.Y_DEN]

# RFC 9380 §8.8.2 effective cofactor for G2 cofactor clearing. This differs
# from the naive twist cofactor H2 = #E'(Fp2)/r by a unit mod r, so it also
# lands points in G2 (asserted in tests), but produces the RFC-specified
# point. Verified against the RFC 9380 J.10.1 test vector.
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def _poly_eval(coeffs, x: Fp2) -> Fp2:
    acc = Fp2.zero()
    for c in reversed(coeffs):
        acc = acc * x + c
    return acc


def iso_map(pt):
    """The derived 3-isogeny E' -> E2; kernel points map to infinity."""
    if pt is None:
        return None
    x, y = pt
    den = _poly_eval(_X_DEN, x)
    if den.is_zero():
        return None
    X = _poly_eval(_X_NUM, x) * den.inv()
    Y = y * _poly_eval(_Y_NUM, x) * _poly_eval(_Y_DEN, x).inv()
    assert Y.square() == X.square() * X + B2
    return (X, Y)


def hash_to_g2(msg: bytes, dst: bytes = params.DST):
    """Full hash_to_curve; returns an affine G2 point.

    Cofactor clearing uses the endomorphism-based fast path (endo.py), which
    is asserted at import time to equal multiplication by H_EFF_G2 on random
    twist points; `hash_to_g2_slow` keeps the literal RFC scalar mul as the
    differential anchor.
    """
    from .endo import clear_cofactor_fast

    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = iso_map(sswu(u0))
    q1 = iso_map(sswu(u1))
    return clear_cofactor_fast(affine_add(q0, q1, Fp2))


def hash_to_g2_slow(msg: bytes, dst: bytes = params.DST):
    """Literal RFC 9380 pipeline with scalar-mul cofactor clearing."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = iso_map(sswu(u0))
    q1 = iso_map(sswu(u1))
    return affine_mul(affine_add(q0, q1, Fp2), H_EFF_G2, Fp2)
