"""Backend-generic BLS API — the analog of the reference's `bls` crate.

The reference exposes generic `TPublicKey`/`TSignature`/`TAggregateSignature`
traits instantiated per backend (blst, fake_crypto) at compile time
(reference: crypto/bls/src/lib.rs:84-139).  Here the same shape is a runtime
registry: `set_backend("python" | "fake" | "jax")`.  The host-side containers
(compressed bytes + decoded points) are shared; only the *verification engine*
differs — which is exactly the boundary the TPU backend needs (it consumes
marshaled signature sets, reference: consensus/state_processing/src/
per_block_processing/signature_sets.rs).

Semantics mirrored from the reference:
  * PublicKey deserialization rejects the point at infinity and runs
    key_validate (crypto/bls/src/generic_public_key.rs:14-15,70).
  * `verify_signature_sets` draws nonzero 64-bit random weights per set,
    subgroup-checks signatures, rejects empty sets, aggregates each set's
    pubkeys, and performs one multi-pairing check
    (crypto/bls/src/impls/blst.rs:35-117).
  * `eth_fast_aggregate_verify` accepts the infinity signature with an empty
    pubkey list (the G2_POINT_AT_INFINITY special case in the spec).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field

from . import params
from .curve import (
    Fp,
    Fp2,
    G1_GENERATOR,
    affine_add,
    affine_mul,
    affine_neg,
    from_jacobian,
    g1_from_bytes,
    g1_to_bytes,
    g2_from_bytes,
    g2_subgroup_check,
    g2_to_bytes,
    jac_add,
    jac_mul,
    to_jacobian,
)
from .hash_to_curve import hash_to_g2
from .pairing import pairing_check

# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------


class BlsError(ValueError):
    pass


class SecretKey:
    __slots__ = ("_sk",)

    def __init__(self, sk: int):
        if not 1 <= sk < params.R:
            raise BlsError("secret key out of range")
        self._sk = sk

    @classmethod
    def random(cls) -> "SecretKey":
        return cls(1 + secrets.randbelow(params.R - 1))

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != params.SCALAR_BYTES:
            raise BlsError("secret key must be 32 bytes")
        v = int.from_bytes(data, "big")
        return cls(v)

    def to_bytes(self) -> bytes:
        return self._sk.to_bytes(params.SCALAR_BYTES, "big")

    def public_key(self) -> "PublicKey":
        return PublicKey(affine_mul(G1_GENERATOR, self._sk, Fp))

    def sign(self, msg: bytes) -> "Signature":
        h = hash_to_g2(msg)
        return Signature(affine_mul(h, self._sk, Fp2))

    @property
    def int_value(self) -> int:
        return self._sk


class PublicKey:
    """A validated, non-infinity G1 point (decompressed)."""

    __slots__ = ("point",)

    def __init__(self, point):
        if point is None:
            # Reference rejects infinity pubkeys at deserialize
            # (generic_public_key.rs:14-15).
            raise BlsError("public key cannot be the point at infinity")
        self.point = point

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        pt = g1_from_bytes(data, subgroup_check=True)
        return cls(pt)

    def to_bytes(self) -> bytes:
        return g1_to_bytes(self.point)

    def __eq__(self, other):
        return isinstance(other, PublicKey) and self.point == other.point

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"PublicKey({self.to_bytes().hex()[:16]}…)"


class AggregatePublicKey:
    """Sum of pubkeys; may be infinity (matches TAggregatePublicKey)."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    @classmethod
    def aggregate(cls, pubkeys: list[PublicKey]) -> "AggregatePublicKey":
        if not pubkeys:
            raise BlsError("cannot aggregate an empty pubkey list")
        acc = to_jacobian(None, Fp)
        for pk in pubkeys:
            acc = jac_add(acc, to_jacobian(pk.point, Fp), Fp)
        return cls(from_jacobian(acc, Fp))


class Signature:
    """A G2 point or infinity.  Subgroup checking is deferred to verification
    time, as in the reference (blst.rs:71-81)."""

    __slots__ = ("point", "_subgroup_checked")

    def __init__(self, point, subgroup_checked: bool = True):
        self.point = point
        self._subgroup_checked = subgroup_checked

    @classmethod
    def infinity(cls) -> "Signature":
        return cls(None)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        # Decode without subgroup check (deferred), matching lazy signature
        # validation in the reference.
        pt = g2_from_bytes(data, subgroup_check=False)
        return cls(pt, subgroup_checked=False)

    def to_bytes(self) -> bytes:
        return g2_to_bytes(self.point)

    def is_infinity(self) -> bool:
        return self.point is None

    def subgroup_check(self) -> bool:
        if self._subgroup_checked:
            return True
        ok = self.point is None or g2_subgroup_check(self.point)
        if ok:
            self._subgroup_checked = True
        return ok

    def __eq__(self, other):
        return isinstance(other, Signature) and self.point == other.point

    def __repr__(self):
        return f"Signature({self.to_bytes().hex()[:16]}…)"


class AggregateSignature:
    __slots__ = ("signature",)

    def __init__(self, signature: Signature | None = None):
        self.signature = signature if signature is not None else Signature.infinity()

    @classmethod
    def aggregate(cls, signatures: list[Signature]) -> "AggregateSignature":
        if not signatures:
            raise BlsError("cannot aggregate an empty signature list")
        acc = to_jacobian(None, Fp2)
        checked = True
        for s in signatures:
            acc = jac_add(acc, to_jacobian(s.point, Fp2), Fp2)
            checked = checked and s._subgroup_checked
        # Subgroup-checkedness propagates only if every input was checked
        # (G2 is a subgroup, so sums of checked points stay inside it);
        # otherwise the deferred check must still run at verify time.
        return cls(Signature(from_jacobian(acc, Fp2), subgroup_checked=checked))

    def add_assign(self, sig: Signature) -> None:
        pt = affine_add(self.signature.point, sig.point, Fp2)
        checked = self.signature._subgroup_checked and sig._subgroup_checked
        self.signature = Signature(pt, subgroup_checked=checked)

    def to_bytes(self) -> bytes:
        return self.signature.to_bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "AggregateSignature":
        return cls(Signature.from_bytes(data))


@dataclass
class SignatureSet:
    """One unit of batch verification: (signature, pubkeys, message).

    Mirrors GenericSignatureSet (reference: crypto/bls/src/
    generic_signature_set.rs:61-121): the signature is valid iff it verifies
    against the aggregate of `signing_keys` over `message`.
    """

    signature: Signature
    signing_keys: list[PublicKey]
    message: bytes  # raw message (for Ethereum: a 32-byte signing root)

    def verify(self) -> bool:
        return get_backend().verify_signature_sets([self])


# ---------------------------------------------------------------------------
# Core verification engines
# ---------------------------------------------------------------------------


class PythonBackend:
    """CPU reference backend (pairing-based, pure Python)."""

    name = "python"

    def verify(self, pubkey: PublicKey, msg: bytes, sig: Signature) -> bool:
        if sig.point is None:
            return False
        if not sig.subgroup_check():
            return False
        h = hash_to_g2(msg)
        return pairing_check(
            [(affine_neg(G1_GENERATOR), sig.point), (pubkey.point, h)]
        )

    def aggregate_verify(
        self, pubkeys: list[PublicKey], msgs: list[bytes], sig: Signature
    ) -> bool:
        """Distinct-message aggregate verification (blst.rs:244-255)."""
        if not pubkeys or len(pubkeys) != len(msgs):
            return False
        if sig.point is None or not sig.subgroup_check():
            return False
        pairs = [(affine_neg(G1_GENERATOR), sig.point)]
        for pk, m in zip(pubkeys, msgs):
            pairs.append((pk.point, hash_to_g2(m)))
        return pairing_check(pairs)

    def fast_aggregate_verify(
        self, pubkeys: list[PublicKey], msg: bytes, sig: Signature
    ) -> bool:
        """Same-message aggregate verification (blst.rs:231-242)."""
        if not pubkeys:
            return False
        if sig.point is None or not sig.subgroup_check():
            return False
        agg = AggregatePublicKey.aggregate(pubkeys)
        if agg.point is None:
            return False
        h = hash_to_g2(msg)
        return pairing_check(
            [(affine_neg(G1_GENERATOR), sig.point), (agg.point, h)]
        )

    def verify_signature_sets(self, sets: list[SignatureSet]) -> bool:
        """Random-linear-combination multi-set verification
        (blst.rs:35-117; SURVEY.md §3.5)."""
        if not sets:
            return False
        pairs = []
        sig_acc = to_jacobian(None, Fp2)  # Σ r_i · sig_i
        for s in sets:
            # Nonzero 64-bit random weight (blst.rs:52-66).
            r = 0
            while r == 0:
                r = secrets.randbits(params.RAND_BITS)
            if s.signature.point is None:
                return False
            if not s.signature.subgroup_check():
                return False
            if not s.signing_keys:
                return False
            agg = AggregatePublicKey.aggregate(s.signing_keys)
            if agg.point is None:
                return False
            sig_acc = jac_add(
                sig_acc,
                jac_mul(to_jacobian(s.signature.point, Fp2), r, Fp2),
                Fp2,
            )
            pairs.append(
                (affine_mul(agg.point, r, Fp), hash_to_g2(s.message))
            )
        pairs.append((affine_neg(G1_GENERATOR), from_jacobian(sig_acc, Fp2)))
        return pairing_check(pairs)


class FakeBackend:
    """Always-valid backend for crypto-independent logic tests — the analog of
    fake_crypto (reference: crypto/bls/src/impls/fake_crypto.rs)."""

    name = "fake"

    def verify(self, pubkey, msg, sig) -> bool:
        return True

    def aggregate_verify(self, pubkeys, msgs, sig) -> bool:
        return True

    def fast_aggregate_verify(self, pubkeys, msg, sig) -> bool:
        return True

    def verify_signature_sets(self, sets) -> bool:
        return True


_BACKENDS: dict[str, object] = {}
_ACTIVE: list[object] = []


def register_backend(backend) -> None:
    _BACKENDS[backend.name] = backend


def set_backend(name: str) -> None:
    if name == "jax" and name not in _BACKENDS:
        # Lazy registration so importing the api never pulls in jax.
        from .jax_backend.backend import register as _register_jax

        _register_jax()
    if name not in _BACKENDS:
        raise KeyError(f"unknown BLS backend {name!r}; have {sorted(_BACKENDS)}")
    _ACTIVE[0] = _BACKENDS[name]


def get_backend():
    return _ACTIVE[0]


def cpu_backend() -> PythonBackend:
    """The always-available pure-Python engine, regardless of which backend
    is active — the degraded-mode fallback the CircuitBreaker routes to
    when the device backend is tripping."""
    return _BACKENDS["python"]


register_backend(PythonBackend())
register_backend(FakeBackend())
_ACTIVE.append(_BACKENDS["python"])


# ---------------------------------------------------------------------------
# Module-level convenience API (the `bls::` free functions of the reference)
# ---------------------------------------------------------------------------


def verify(pubkey: PublicKey, msg: bytes, sig: Signature) -> bool:
    return get_backend().verify(pubkey, msg, sig)


def aggregate_verify(pubkeys, msgs, sig) -> bool:
    return get_backend().aggregate_verify(pubkeys, msgs, sig)


def fast_aggregate_verify(pubkeys, msg, sig) -> bool:
    return get_backend().fast_aggregate_verify(pubkeys, msg, sig)


def eth_fast_aggregate_verify(pubkeys, msg, sig) -> bool:
    """Spec variant: infinity signature over zero pubkeys is valid
    (used by sync-committee verification)."""
    if not pubkeys and sig.is_infinity():
        return True
    return fast_aggregate_verify(pubkeys, msg, sig)


def verify_signature_sets(sets: list[SignatureSet]) -> bool:
    return get_backend().verify_signature_sets(sets)
