"""EIP-2335 keystores: password-encrypted BLS secret keys.

Twin of crypto/eth2_keystore (Keystore at src/keystore.rs): scrypt or
pbkdf2 KDF (hashlib), AES-128-CTR cipher (cryptography package),
sha256 checksum binding KDF output to ciphertext.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid as uuid_mod

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


class KeystoreError(ValueError):
    pass


def _scrypt(password: bytes, salt: bytes, n: int, r: int, p: int, dklen: int):
    return hashlib.scrypt(
        password, salt=salt, n=n, r=r, p=p, dklen=dklen, maxmem=2**31 - 1
    )


def _pbkdf2(password: bytes, salt: bytes, c: int, dklen: int):
    return hashlib.pbkdf2_hmac("sha256", password, salt, c, dklen)


def _process_password(password: str) -> bytes:
    """EIP-2335: NFKD normalize, strip C0/C1 control codes."""
    import unicodedata

    norm = unicodedata.normalize("NFKD", password)
    return "".join(
        c for c in norm if not (ord(c) < 0x20 or 0x7F <= ord(c) < 0xA0)
    ).encode()


def _aes128ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


def encrypt(
    secret: bytes,
    password: str,
    path: str = "",
    kdf: str = "scrypt",
    pubkey: bytes | None = None,
    description: str = "",
) -> dict:
    """Build the EIP-2335 keystore JSON object."""
    salt = os.urandom(32)
    iv = os.urandom(16)
    pw = _process_password(password)
    if kdf == "scrypt":
        params = {"dklen": 32, "n": 262144, "r": 8, "p": 1, "salt": salt.hex()}
        dk = _scrypt(pw, salt, params["n"], params["r"], params["p"], 32)
    elif kdf == "pbkdf2":
        params = {"dklen": 32, "c": 262144, "prf": "hmac-sha256", "salt": salt.hex()}
        dk = _pbkdf2(pw, salt, params["c"], 32)
    else:
        raise KeystoreError(f"unsupported kdf {kdf}")
    cipher_text = _aes128ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    return {
        "crypto": {
            "kdf": {"function": kdf, "params": params, "message": ""},
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": checksum.hex(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_text.hex(),
            },
        },
        "description": description,
        "pubkey": pubkey.hex() if pubkey else "",
        "path": path,
        "uuid": str(uuid_mod.uuid4()),
        "version": 4,
    }


def decrypt(keystore: dict | str, password: str) -> bytes:
    """Recover the secret; KeystoreError on wrong password (checksum)."""
    ks = json.loads(keystore) if isinstance(keystore, str) else keystore
    if ks.get("version") != 4:
        raise KeystoreError("only EIP-2335 v4 keystores supported")
    crypto = ks["crypto"]
    kdf = crypto["kdf"]["function"]
    params = crypto["kdf"]["params"]
    salt = bytes.fromhex(params["salt"])
    pw = _process_password(password)
    if kdf == "scrypt":
        dk = _scrypt(pw, salt, params["n"], params["r"], params["p"], params["dklen"])
    elif kdf == "pbkdf2":
        dk = _pbkdf2(pw, salt, params["c"], params["dklen"])
    else:
        raise KeystoreError(f"unsupported kdf {kdf}")
    cipher_text = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise KeystoreError("invalid password (checksum mismatch)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128ctr(dk[:16], iv, cipher_text)
