"""EIP-2333 hierarchical BLS key derivation.

Twin of crypto/eth2_key_derivation (DerivedKey, Lamport keys): HKDF-SHA256
master-key derivation from seed, Lamport-based child derivation, and EIP-
2334 path parsing (m/12381/3600/i/0/0).  Anchored by the published EIP-2333
test vector in tests/test_keys.py.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod

from .bls.params import R as CURVE_ORDER


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac_mod.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hmac_mod.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    """IETF BLS KeyGen: repeat HKDF until nonzero mod r."""
    salt = b"BLS-SIG-KEYGEN-SALT-"
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % CURVE_ORDER
    return sk


def _ikm_to_lamport_sk(ikm: bytes, salt: bytes) -> list[bytes]:
    prk = _hkdf_extract(salt, ikm)
    okm = _hkdf_expand(prk, b"", 255 * 32)
    return [okm[i * 32 : (i + 1) * 32] for i in range(255)]


def _parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    lamport_0 = _ikm_to_lamport_sk(ikm, salt)
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    lamport_1 = _ikm_to_lamport_sk(not_ikm, salt)
    pk = b"".join(hashlib.sha256(x).digest() for x in lamport_0 + lamport_1)
    return hashlib.sha256(pk).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("seed must be at least 32 bytes")
    return hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    return hkdf_mod_r(_parent_sk_to_lamport_pk(parent_sk, index))


def derive_path(seed: bytes, path: str) -> int:
    """EIP-2334 path, e.g. 'm/12381/3600/0/0/0'."""
    parts = path.strip().split("/")
    if parts[0] != "m":
        raise ValueError("path must start with m")
    sk = derive_master_sk(seed)
    for p in parts[1:]:
        sk = derive_child_sk(sk, int(p))
    return sk


def validator_signing_path(index: int) -> str:
    return f"m/12381/3600/{index}/0/0"


def validator_withdrawal_path(index: int) -> str:
    return f"m/12381/3600/{index}/0"
